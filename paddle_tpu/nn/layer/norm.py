"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np

from ...core import dtype as dtypes
from ...core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from .layers import Layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is not False:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """Fused RMS norm (reference: paddle incubate rms_norm + phi
    fusion/gpu/rms_norm_kernel)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None
        import jax.numpy as jnp
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features,
                                                       dtype=jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features,
                                                          dtype=jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under GSPMD/jit the batch axis is sharded and XLA
    computes global statistics automatically when the reduction spans the
    mesh; the eager single-process path equals BatchNorm (reference:
    python/paddle/nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            out = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
            if layer.bias is not None:
                out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            if sub is not None:
                out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_channels], attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.weight = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_channels], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is not False:
            self.scale = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
        else:
            self.scale = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Power-iteration spectral norm of a weight (reference:
    python/paddle/nn/layer/norm.py SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        import jax.numpy as jnp
        from ...core.generator import next_key
        import jax
        self.weight_u = self.create_parameter([h], attr=None,
                                              default_initializer=None)
        self.weight_u.set_value(jax.random.normal(next_key(), (h,)))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter([w], attr=None,
                                              default_initializer=None)
        self.weight_v.set_value(jax.random.normal(next_key(), (w,)))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        import jax.numpy as jnp
        from ...core import dispatch
        from ...core.tensor import as_tensor
        w = weight if isinstance(weight, Tensor) else as_tensor(weight)
        dim, eps, iters = self._dim, self._epsilon, self._power_iters
        u0, v0 = self.weight_u._data, self.weight_v._data

        def f(wa):
            perm = [dim] + [i for i in range(wa.ndim) if i != dim]
            m = jnp.transpose(wa, perm).reshape(wa.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = m.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = m @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ m @ v
            return wa / sigma
        return dispatch.call("spectral_norm", f, [w])
