"""Containers (reference: python/paddle/nn/layer/container.py)."""
from __future__ import annotations

import collections

from ..parameter import Parameter
from .layers import Layer


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, l in layers[0].items():
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, (list, tuple)) and len(l) == 2:
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers.keys())
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        if idx < 0:
            idx += len(self)
        return self._sub_layers[str(idx)]

    def __setitem__(self, idx, layer):
        if idx < 0:
            idx += len(self)
        self._sub_layers[str(idx)] = layer

    def __delitem__(self, idx):
        if isinstance(idx, slice):
            for k in list(self._sub_layers.keys())[idx]:
                del self._sub_layers[k]
        else:
            if idx < 0:
                idx += len(self)
            del self._sub_layers[str(idx)]
        # re-number
        layers = list(self._sub_layers.values())
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        l = self._sub_layers.pop(key)
        return l

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        if isinstance(sublayers, dict):
            sublayers = sublayers.items()
        for k, v in sublayers:
            self.add_sublayer(k, v)


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self)), parameter)
        return self
