from .layers import Layer
from .activation import *  # noqa: F401,F403
from .common import *      # noqa: F401,F403
from .container import Sequential, LayerList, LayerDict, ParameterList
from .conv import (Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose,
                   Conv3DTranspose)
from .loss import *        # noqa: F401,F403
from .norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                   GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
                   LayerNorm, LocalResponseNorm, RMSNorm, SpectralNorm,
                   SyncBatchNorm)
from .pooling import (AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
                      AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
                      AvgPool1D, AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D,
                      MaxPool3D)
from .transformer import (MultiHeadAttention, Transformer, TransformerDecoder,
                          TransformerDecoderLayer, TransformerEncoder,
                          TransformerEncoderLayer)
from .rnn import (RNN, BiRNN, GRU, GRUCell, LSTM, LSTMCell, RNNCellBase,
                  SimpleRNN, SimpleRNNCell)
from .tail import *        # noqa: F401,F403
