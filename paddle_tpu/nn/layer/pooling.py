"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.exclusive, self.ceil_mode = exclusive, ceil_mode

    def forward(self, x):
        return F.avg_pool1d(x, self.k, self.s, self.p, self.exclusive,
                            self.ceil_mode)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.ceil_mode, self.exclusive = ceil_mode, exclusive
        self.divisor_override = divisor_override
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, self.k, self.s, self.p, self.ceil_mode,
                            self.exclusive, self.divisor_override,
                            self.data_format)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.ceil_mode, self.exclusive = ceil_mode, exclusive
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool3d(x, self.k, self.s, self.p, self.ceil_mode,
                            self.exclusive, None, self.data_format)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.return_mask, self.ceil_mode = return_mask, ceil_mode

    def forward(self, x):
        return F.max_pool1d(x, self.k, self.s, self.p, self.return_mask,
                            self.ceil_mode)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.return_mask, self.ceil_mode = return_mask, ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(x, self.k, self.s, self.p, self.return_mask,
                            self.ceil_mode, self.data_format)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.return_mask, self.ceil_mode = return_mask, ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool3d(x, self.k, self.s, self.p, self.return_mask,
                            self.ceil_mode, self.data_format)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)
