"""Parameter: a trainable Tensor, plus ParamAttr.

Reference: python/paddle/base/framework.py EagerParamBase (Parameter is a
Tensor with trainable/optimize attrs); paddle.ParamAttr
(python/paddle/base/param_attr.py).
"""
from __future__ import annotations

import itertools
from typing import Optional

from ..core import dtype as dtypes
from ..core.tensor import Tensor

_param_counter = itertools.count()


class ParamAttr:
    def __init__(self, name: Optional[str] = None, initializer=None,
                 learning_rate: float = 1.0, regularizer=None,
                 trainable: bool = True, need_clip: bool = True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return None
        # an initializer instance used directly as attr
        return ParamAttr(initializer=attr)


class Parameter(Tensor):
    def __init__(self, data, *, trainable: bool = True, name: Optional[str] = None,
                 optimize_attr=None, regularizer=None, need_clip: bool = True):
        super().__init__(data, stop_gradient=not trainable,
                         name=name or f"param_{next(_param_counter)}",
                         persistable=True)
        self.trainable = trainable
        self.optimize_attr = optimize_attr or {"learning_rate": 1.0}
        self.regularizer = regularizer
        self.need_clip = need_clip
        self.is_distributed = False
        # HBM attribution: the perf memory census reports this buffer
        # (and its .grad) under the "params"/"grads" tags
        from ..observability.perf import memory as _perf_memory
        _perf_memory.track_parameter(self)

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def create_parameter(shape, dtype=dtypes.float32, attr=None, is_bias=False,
                     default_initializer=None) -> Optional[Parameter]:
    """Materialize a Parameter per attr/initializer precedence
    (reference: python/paddle/nn/layer/layers.py create_parameter)."""
    from . import initializer as I

    attr = ParamAttr._to_attr(attr)
    if attr is None:
        return None
    init = attr.initializer or default_initializer
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    dtype = dtypes.convert_dtype(dtype)
    from . import lazy_init
    if lazy_init.in_lazy_mode():
        # defer: shape/dtype inspection works via ShapeDtypeStruct,
        # compute waits for materialization (reference LazyGuard)
        import jax
        data = jax.ShapeDtypeStruct(tuple(shape), dtype)
    else:
        data = init(shape, dtype)
    p = Parameter(data, trainable=attr.trainable, name=attr.name)
    if lazy_init.in_lazy_mode():
        lazy_init._register(p, init, shape, dtype)
    p.optimize_attr = {"learning_rate": attr.learning_rate}
    p.regularizer = attr.regularizer
    p.need_clip = attr.need_clip
    return p
