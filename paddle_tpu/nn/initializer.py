"""Weight initializers.

Capability parity with the reference's initializer suite (reference:
python/paddle/nn/initializer/*.py — Constant, Normal, TruncatedNormal,
Uniform, Xavier*, Kaiming*, Assign, Orthogonal). TPU-native: each initializer
is a pure function of (shape, dtype, key) using the counter-based global
generator, so initialization is reproducible from ``paddle.seed`` and usable
under capture.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.generator import next_key
from ..core.tensor import Tensor


class Initializer:
    def __call__(self, shape, dtype=dtypes.float32):
        raise NotImplementedError

    def apply(self, tensor: Tensor):
        tensor.set_value(self(tensor.shape, tensor.dtype))
        return tensor


def _fan_in_out(shape):
    shape = list(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # Linear weight is (in_features, out_features) in the reference.
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=dtypes.float32):
        return jnp.full(tuple(shape), self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=dtypes.float32):
        return self.mean + self.std * jax.random.normal(
            next_key(), tuple(shape), dtype=dtype)


class TruncatedNormal(Initializer):
    """Normal truncated to [mean - a*std, mean + b*std] (default 2 std)."""

    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype=dtypes.float32):
        r = jax.random.truncated_normal(next_key(), self.a, self.b,
                                        tuple(shape), dtype=dtype)
        return self.mean + self.std * r


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=dtypes.float32):
        return jax.random.uniform(next_key(), tuple(shape), dtype=dtype,
                                  minval=self.low, maxval=self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=dtypes.float32):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(next_key(), tuple(shape), dtype=dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=dtypes.float32):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), tuple(shape), dtype=dtype,
                                  minval=-limit, maxval=limit)


def _kaiming_gain(negative_slope, nonlinearity):
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        return math.sqrt(2.0 / (1 + negative_slope ** 2))
    if nonlinearity in ("tanh",):
        return 5.0 / 3
    return 1.0


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=dtypes.float32):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        std = _kaiming_gain(self.negative_slope, self.nonlinearity) / math.sqrt(fi)
        return std * jax.random.normal(next_key(), tuple(shape), dtype=dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=dtypes.float32):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        limit = (_kaiming_gain(self.negative_slope, self.nonlinearity)
                 * math.sqrt(3.0 / fi))
        return jax.random.uniform(next_key(), tuple(shape), dtype=dtype,
                                  minval=-limit, maxval=limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=dtypes.float32):
        v = self.value
        if isinstance(v, Tensor):
            v = v._data
        arr = jnp.asarray(v, dtype=dtype)
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(tuple(shape))
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=dtypes.float32):
        shape = tuple(shape)
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jax.random.normal(next_key(), (max(rows, cols), min(rows, cols)),
                                 dtype=jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    """Identity-preserving conv init (reference nn/initializer/dirac.py)."""

    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=dtypes.float32):
        shape = tuple(shape)
        arr = np.zeros(shape, dtype=np.float32)
        out_per_group = shape[0] // self.groups
        mid = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(min(out_per_group, shape[1])):
                arr[(g * out_per_group + i, i) + mid] = 1.0
        return jnp.asarray(arr, dtype=dtype)


# functional aliases matching paddle.nn.initializer module surface
constant = Constant
normal = Normal
uniform = Uniform
xavier_normal = XavierNormal
xavier_uniform = XavierUniform
kaiming_normal = KaimingNormal
kaiming_uniform = KaimingUniform

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac",
]
