"""Seq2seq decoding: Decoder, BeamSearchDecoder, dynamic_decode.

Reference contract: ``python/paddle/nn/decode.py`` (Decoder :42 abstract
initialize/step/finalize; BeamSearchDecoder :153 — beam expansion with
the log-softmax + finished-beam masking of ``_beam_search_step``, state
gathering by parent beam, gather_tree backtrace in finalize :630;
``dynamic_decode`` :994 loops step() until all beams finish or
``max_step_num``).

TPU-native notes: the per-step math is jnp (one fused XLA program per
step under the dispatch pipeline). With ``max_step_num`` given, the
whole decode loop runs IN-GRAPH as one ``lax.while_loop`` — fixed-size
output buffers written with ``.at[t].set``, early exit when every beam
finishes, and a single host sync at the end to trim the buffers to the
realized length (the reference host loop synced once per step). The
unbounded ``max_step_num=None`` path stays host-driven like the
reference dygraph loop: without a step bound there is no fixed output
shape for XLA, so the per-step finished check MUST read device state.
Beam bookkeeping follows the reference: finished beams may only extend
with ``end_token`` (zero log-prob there, -1e9 elsewhere), lengths
freeze once finished, and the final ids come from ``gather_tree`` over
(predicted_ids, parent_ids).
"""
from __future__ import annotations

from collections import namedtuple
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, as_tensor

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]

_KINF = 1e9


class Decoder:
    """Abstract decoder (reference decode.py:42)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self) -> bool:
        return False


_State = namedtuple("BeamSearchState",
                    ["cell_states", "log_probs", "finished", "lengths"])
_Output = namedtuple("BeamSearchOutput",
                     ["scores", "predicted_ids", "parent_ids"])


def _map_structure(fn, structure):
    if isinstance(structure, (list, tuple)):
        out = [_map_structure(fn, s) for s in structure]
        return type(structure)(out) if not hasattr(structure, "_fields") \
            else type(structure)(*out)
    if isinstance(structure, dict):
        return {k: _map_structure(fn, v) for k, v in structure.items()}
    return fn(structure)


def _first_leaf(structure):
    if isinstance(structure, (list, tuple)):
        return _first_leaf(structure[0])
    if isinstance(structure, dict):
        return _first_leaf(next(iter(structure.values())))
    return structure


class BeamSearchDecoder(Decoder):
    """Beam search over an RNN-style cell (reference decode.py:153).

    ``cell(inputs, states)`` → (outputs, next_states);
    ``output_fn`` maps cell outputs to vocab logits; ``embedding_fn``
    maps token ids to cell inputs.
    """

    OutputWrapper = _Output
    StateWrapper = _State

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn: Optional[Callable] = None,
                 output_fn: Optional[Callable] = None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # ------------------------------------------------------------ helpers
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] → [batch * beam, ...] (reference :241)."""
        x = as_tensor(x)
        a = x._data
        tiled = jnp.repeat(a[:, None], beam_size, axis=1)
        return Tensor(tiled.reshape((-1,) + a.shape[1:]))

    def _expand_to_beam_size(self, x):
        x = as_tensor(x)
        a = x._data
        return Tensor(jnp.repeat(a[:, None], self.beam_size, axis=1))

    def _merge_batch_beams(self, x):
        x = as_tensor(x)
        a = x._data
        return Tensor(a.reshape((-1,) + a.shape[2:]))

    def _split_batch_beams(self, x):
        x = as_tensor(x)
        a = x._data
        return Tensor(a.reshape((-1, self.beam_size) + a.shape[1:]))

    @staticmethod
    def _gather(x, indices, batch_size):
        """Per-batch gather along the beam axis."""
        a = as_tensor(x)._data
        idx = as_tensor(indices)._data.astype(jnp.int32)
        return Tensor(jnp.take_along_axis(
            a, idx.reshape(idx.shape + (1,) * (a.ndim - 2)), axis=1))

    # ---------------------------------------------------------- contract
    def initialize(self, initial_cell_states):
        state0 = _first_leaf(initial_cell_states)
        batch = as_tensor(state0).shape[0]
        self.batch_size = batch
        cell_states = _map_structure(self._expand_to_beam_size,
                                     initial_cell_states)
        init_ids = Tensor(jnp.full((batch, self.beam_size),
                                   self.start_token, jnp.int32))
        log_probs = Tensor(jnp.tile(jnp.array(
            [[0.0] + [-_KINF] * (self.beam_size - 1)], jnp.float32),
            (batch, 1)))
        finished = Tensor(jnp.zeros((batch, self.beam_size), bool))
        lengths = Tensor(jnp.zeros((batch, self.beam_size), jnp.int32))
        inputs = (self.embedding_fn(init_ids) if self.embedding_fn
                  else init_ids)
        return inputs, _State(cell_states, log_probs, finished,
                              lengths), finished

    def _beam_search_step(self, time, logits, next_cell_states, beam_state):
        la = as_tensor(logits)._data.astype(jnp.float32)
        vocab = la.shape[-1]
        step_logp = jax.nn.log_softmax(la, axis=-1)
        # finished beams: only end_token continues (reference _mask_probs)
        noend = jnp.full((vocab,), -_KINF, jnp.float32).at[
            self.end_token].set(0.0)
        fin = beam_state.finished._data
        step_logp = jnp.where(fin[..., None], noend, step_logp)

        log_probs = step_logp + beam_state.log_probs._data[..., None]
        flat = log_probs.reshape(-1, self.beam_size * vocab)
        topk_scores, topk_idx = jax.lax.top_k(flat, self.beam_size)
        beam_idx = Tensor(topk_idx // vocab)
        token_idx = topk_idx % vocab

        next_cell_states = _map_structure(
            lambda x: self._gather(x, beam_idx, self.batch_size),
            next_cell_states)
        next_finished = self._gather(
            beam_state.finished, beam_idx, self.batch_size)._data
        next_lengths = self._gather(
            beam_state.lengths, beam_idx, self.batch_size)._data
        next_lengths = next_lengths + (~next_finished).astype(jnp.int32)
        next_finished = next_finished | (token_idx == self.end_token)

        output = _Output(Tensor(topk_scores), Tensor(token_idx),
                         beam_idx)
        state = _State(next_cell_states, Tensor(topk_scores),
                       Tensor(next_finished), Tensor(next_lengths))
        return output, state

    def step(self, time, inputs, states, **kwargs):
        merged_inputs = _map_structure(self._merge_batch_beams, inputs)
        merged_cell = _map_structure(self._merge_batch_beams,
                                     states.cell_states)
        cell_out, next_cell = self.cell(merged_inputs, merged_cell,
                                        **kwargs)
        cell_out = _map_structure(self._split_batch_beams, cell_out)
        next_cell = _map_structure(self._split_batch_beams, next_cell)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        output, state = self._beam_search_step(
            time, cell_out, next_cell, states)
        sample_ids = output.predicted_ids
        sample_ids.stop_gradient = True
        next_inputs = (self.embedding_fn(sample_ids) if self.embedding_fn
                       else sample_ids)
        return output, state, next_inputs, state.finished

    def finalize(self, outputs, final_states, sequence_lengths):
        from ..ops.search import gather_tree
        predicted = gather_tree(outputs.predicted_ids,
                                outputs.parent_ids)
        return predicted, final_states


def _raw(structure):
    return _map_structure(lambda x: as_tensor(x)._data, structure)


def _wrap(structure):
    return _map_structure(Tensor, structure)


def _decode_bounded(decoder, inits, max_step_num, **kwargs):
    """Bounded decode as ONE in-graph ``lax.while_loop``.

    The reference host loop runs steps for ``t = 0..max_step_num`` with
    an early break once every beam finishes — and pays one device→host
    sync PER STEP for that finished check. Here the loop, its early
    exit, and the output accumulation (fixed ``max_step_num + 1`` row
    buffers, ``.at[t].set``) are a single XLA program; only the final
    buffer trim reads the realized step count back to the host.
    Namedtuple states/outputs ride the loop carry as plain jax pytrees
    (raw arrays — :class:`Tensor` is not a registered pytree)."""
    from jax import lax

    inputs, states, finished = decoder.initialize(inits)
    n_steps = int(max_step_num) + 1     # host loop runs t = 0..max

    def step_fn(t, inputs_r, states_r, finished_r):
        out, nstates, ninputs, nfin = decoder.step(
            Tensor(jnp.full((1,), t, jnp.int32)), _wrap(inputs_r),
            _wrap(states_r), **kwargs)
        nf = as_tensor(nfin)._data
        if not decoder.tracks_own_finished:
            nf = nf | finished_r
        return _raw(out), _raw(nstates), _raw(ninputs), nf

    carry0 = (jnp.asarray(0, jnp.int32), _raw(inputs), _raw(states),
              _raw(finished))
    out_sds, _s, _i, _f = jax.eval_shape(
        lambda i, s, f: step_fn(0, i, s, f), *carry0[1:])
    bufs0 = jax.tree_util.tree_map(
        lambda sd: jnp.zeros((n_steps,) + tuple(sd.shape), sd.dtype),
        out_sds)

    def cond(carry):
        t, _inputs, _states, fin, _bufs = carry
        # the first step always runs (reference loop is do-while); after
        # that: more steps remain AND some beam is still live
        return (t == 0) | ((t < n_steps) & ~jnp.all(fin))

    def body(carry):
        t, inputs_r, states_r, fin, bufs = carry
        out_r, states_r, inputs_r, fin = step_fn(
            t, inputs_r, states_r, fin)
        bufs = jax.tree_util.tree_map(
            lambda b, o: b.at[t].set(o), bufs, out_r)
        return t + 1, inputs_r, states_r, fin, bufs

    t_end, _inputs_r, states_r, _fin_r, bufs = lax.while_loop(
        cond, body, carry0 + (bufs0,))
    # the ONLY host sync of the bounded path: trim the fixed buffers to
    # the realized decode length (rows past t_end were never written)
    steps = int(np.asarray(t_end))  # tpulint: disable=TPU103,TPU104 — one deliberate sync per decode (not per step): the realized length is dynamic and the trimmed host-facing output shape needs it
    stacked = _map_structure(lambda b: Tensor(b[:steps]), bufs)
    return stacked, _wrap(states_r)


def _decode_host(decoder, inits, max_step_num, impute_finished, **kwargs):
    """Unbounded decode: the reference dygraph host loop. Without a
    step bound there is no fixed output shape for an XLA while_loop, so
    the loop must live on the host and the per-step all-finished check
    necessarily reads device state."""
    inputs, states, finished = decoder.initialize(inits)
    step_outputs = []
    t = 0
    while True:
        output, next_states, next_inputs, next_finished = decoder.step(
            as_tensor(np.array([t], np.int64)), inputs, states, **kwargs)
        if not decoder.tracks_own_finished:
            nf = Tensor(as_tensor(next_finished)._data
                        | as_tensor(finished)._data)
        else:
            nf = as_tensor(next_finished)
        if impute_finished:
            # freeze states of finished beams (reference impute_finished)
            next_states = _map_structure(
                lambda new: new, next_states)
        step_outputs.append(output)
        inputs, states, finished = next_inputs, next_states, nf
        t += 1
        done = bool(np.asarray(finished._data).all())  # tpulint: disable=TPU103,TPU104 — unbounded loop termination is inherently a host decision; the bounded path (max_step_num given) runs in-graph
        if done or (max_step_num is not None and t > int(max_step_num)):
            break

    stacked = _Output(*[
        Tensor(jnp.stack([as_tensor(getattr(o, f))._data
                          for o in step_outputs]))
        for f in _Output._fields])
    return stacked, states


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Loop ``decoder.step`` until every beam finishes (reference
    decode.py:994). With ``max_step_num`` the loop runs in-graph as a
    single ``lax.while_loop`` program (one host sync per decode); the
    unbounded form keeps the reference host loop."""
    if max_step_num is not None and not impute_finished:
        stacked, states = _decode_bounded(decoder, inits,
                                          int(max_step_num), **kwargs)
    else:
        stacked, states = _decode_host(decoder, inits, max_step_num,
                                       impute_finished, **kwargs)
    seq_lengths = getattr(states, "lengths", None)
    if hasattr(decoder, "finalize"):
        try:
            final_outputs, final_states = decoder.finalize(
                stacked, states, seq_lengths)
        except NotImplementedError:
            final_outputs, final_states = stacked, states
    else:
        final_outputs, final_states = stacked, states

    if not output_time_major:
        final_outputs = _map_structure(
            lambda x: Tensor(jnp.swapaxes(as_tensor(x)._data, 0, 1)),
            final_outputs)
    if return_length:
        return final_outputs, final_states, seq_lengths
    return final_outputs, final_states
