"""Convolution functionals.

Reference: python/paddle/nn/functional/conv.py (conv2d -> phi conv kernels,
paddle/phi/kernels/gpu/conv_kernel.cu via cuDNN). TPU-native: one
``lax.conv_general_dilated`` lowering — XLA maps it onto the MXU and picks
the layout; there is no algo-autotune cache to port because XLA owns it.
Weight layouts follow the reference: conv NCHW/OIHW, conv_transpose IOHW.
"""
from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core.tensor import Tensor, as_tensor


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        raise ValueError(f"expected {n} values, got {v}")
    return tuple(int(v) for _ in range(n))


def _resolve_padding(padding, nd, dilation, ksize):
    """Paddle padding forms: int, list, 'SAME', 'VALID', per-dim pairs."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        flat = list(padding)
        if len(flat) == nd and all(isinstance(p, (list, tuple)) for p in flat):
            return [tuple(p) for p in flat]
        if len(flat) == 2 * nd:
            return [(flat[2 * i], flat[2 * i + 1]) for i in range(nd)]
        p = _ntuple(flat, nd)
        return [(x, x) for x in p]
    p = _ntuple(padding, nd)
    return [(x, x) for x in p]


def _conv_nd(x, w, bias, stride, padding, dilation, groups, nd, channel_last,
             op_name):
    stride = _ntuple(stride, nd)
    dilation = _ntuple(dilation, nd)
    spatial = "DHW"[3 - nd:]
    if channel_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    dn = jax.lax.conv_dimension_numbers(
        tuple(x.shape), tuple(w.shape), (lhs_spec, "OI" + spatial, lhs_spec))
    pad = _resolve_padding(padding, nd, dilation, w.shape[2:])

    inputs = [x, w] + ([bias] if bias is not None else [])

    def f(a, wt, *rest):
        y = jax.lax.conv_general_dilated(
            a, wt.astype(a.dtype), window_strides=stride, padding=pad,
            rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=groups)
        if rest:
            b = rest[0].astype(y.dtype)
            shape = [1] * y.ndim
            shape[-1 if channel_last else 1] = b.size
            y = y + b.reshape(shape)
        return y
    return dispatch.call(op_name, f, inputs, export_attrs={
        "stride": stride, "padding": pad, "dilation": dilation,
        "groups": groups, "channel_last": channel_last})


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    """1D convolution via lax.conv_general_dilated, NCL layout (reference
    conv1d)."""
    return _conv_nd(_t(x), _t(weight), _t(bias) if bias is not None else None,
                    stride, padding, dilation, groups, 1,
                    data_format == "NLC", "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """2D convolution via lax.conv_general_dilated, NCHW layout, groups
    supported (reference conv2d)."""
    return _conv_nd(_t(x), _t(weight), _t(bias) if bias is not None else None,
                    stride, padding, dilation, groups, 2,
                    data_format == "NHWC", "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    """3D convolution via lax.conv_general_dilated, NCDHW layout (reference
    conv3d)."""
    return _conv_nd(_t(x), _t(weight), _t(bias) if bias is not None else None,
                    stride, padding, dilation, groups, 3,
                    data_format == "NDHWC", "conv3d")


def _conv_transpose_nd(x, w, bias, stride, padding, output_padding, dilation,
                       groups, nd, channel_last, output_size, op_name):
    stride = _ntuple(stride, nd)
    dilation = _ntuple(dilation, nd)
    output_padding = _ntuple(output_padding, nd)
    ksize = [int(k) for k in w.shape[2:]]
    pad = _resolve_padding(padding, nd, dilation, ksize)
    if isinstance(pad, str):
        if pad == "VALID":
            pad = [(0, 0)] * nd
        else:  # SAME: out = in * stride
            pad = []
            for i in range(nd):
                total = dilation[i] * (ksize[i] - 1) + 1 - stride[i]
                total = max(total, 0)
                pad.append((total // 2, total - total // 2))
    if output_size is not None:
        output_size = _ntuple(output_size, nd)
        in_spatial = x.shape[2:] if not channel_last else x.shape[1:-1]
        output_padding = tuple(
            output_size[i] - ((in_spatial[i] - 1) * stride[i]
                              - pad[i][0] - pad[i][1]
                              + dilation[i] * (ksize[i] - 1) + 1)
            for i in range(nd))

    spatial = "DHW"[3 - nd:]
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)

    c_in = w.shape[0]
    c_out_per_g = w.shape[1]

    # Gradient-of-conv formulation: flip spatial dims, swap I/O per group,
    # dilate the input by stride (reference semantics:
    # python/paddle/nn/functional/conv.py conv2d_transpose).
    conv_pad = [
        (dilation[i] * (ksize[i] - 1) - pad[i][0],
         dilation[i] * (ksize[i] - 1) - pad[i][1] + output_padding[i])
        for i in range(nd)
    ]

    inputs = [x, w] + ([bias] if bias is not None else [])

    def f(a, wt, *rest):
        g = groups
        kt = wt.reshape((g, c_in // g, c_out_per_g) + wt.shape[2:])
        kt = jnp.swapaxes(kt, 1, 2)
        kt = kt.reshape((g * c_out_per_g, c_in // g) + wt.shape[2:])
        kt = jnp.flip(kt, axis=tuple(range(2, 2 + nd)))
        dn = jax.lax.conv_dimension_numbers(
            tuple(a.shape), tuple(kt.shape), (lhs_spec, "OI" + spatial, lhs_spec))
        y = jax.lax.conv_general_dilated(
            a, kt.astype(a.dtype), window_strides=(1,) * nd, padding=conv_pad,
            lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn,
            feature_group_count=g)
        if rest:
            b = rest[0].astype(y.dtype)
            shape = [1] * y.ndim
            shape[-1 if channel_last else 1] = b.size
            y = y + b.reshape(shape)
        return y
    return dispatch.call(op_name, f, inputs)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    """1D transposed (fractionally-strided) convolution (reference
    conv1d_transpose)."""
    return _conv_transpose_nd(_t(x), _t(weight),
                              _t(bias) if bias is not None else None,
                              stride, padding, output_padding, dilation, groups,
                              1, data_format == "NLC", output_size,
                              "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    """2D transposed convolution via lhs dilation (reference conv2d_transpose).
    """
    return _conv_transpose_nd(_t(x), _t(weight),
                              _t(bias) if bias is not None else None,
                              stride, padding, output_padding, dilation, groups,
                              2, data_format == "NHWC", output_size,
                              "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    """3D transposed convolution via lhs dilation (reference conv3d_transpose).
    """
    return _conv_transpose_nd(_t(x), _t(weight),
                              _t(bias) if bias is not None else None,
                              stride, padding, output_padding, dilation, groups,
                              3, data_format == "NDHWC", output_size,
                              "conv3d_transpose")


__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]
