"""Normalization functionals.

Reference: python/paddle/nn/functional/norm.py and the fused kernels
(paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu, rms_norm). On TPU
these are plain jnp expressions XLA fuses into single VPU passes; a Pallas
fused variant exists in paddle_tpu.ops.pallas for the hot path.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core import dispatch
from ...core.tensor import Tensor, as_tensor


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    """Normalize over trailing normalized_shape dims with affine scale/shift
    (reference layer_norm)."""
    x = _t(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    ndim = len(normalized_shape)
    axes = tuple(range(x.ndim - ndim, x.ndim))
    inputs = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        inputs.append(_t(weight))
    if has_b:
        inputs.append(_t(bias))

    def f(a, *wb, **_attrs):
        # semantic attrs ride the IR record (dispatch passes them back as
        # kwargs); the lowering itself closes over the python values
        dt = a.dtype
        a32 = a.astype(jnp.float32)
        mean = jnp.mean(a32, axis=axes, keepdims=True)
        var = jnp.var(a32, axis=axes, keepdims=True)
        y = (a32 - mean) / jnp.sqrt(var + epsilon)
        i = 0
        if has_w:
            y = y * wb[i].astype(jnp.float32)
            i += 1
        if has_b:
            y = y + wb[i].astype(jnp.float32)
        return y.astype(dt)
    # semantic attrs ride the IR record (compile/fusion reads epsilon +
    # the normalized-dim count + affine layout to build the rewrite)
    return dispatch.call("layer_norm", f, inputs,
                         attrs={"epsilon": epsilon, "norm_ndim": ndim,
                                "has_w": has_w, "has_b": has_b})


def rms_norm(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1,
             name=None):
    """x / rms(x) * weight — LayerNorm without mean-centering (reference
    rms_norm)."""
    x = _t(x)
    axis = begin_norm_axis if begin_norm_axis >= 0 else x.ndim + begin_norm_axis
    axes = tuple(range(axis, x.ndim))
    inputs = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        inputs.append(_t(weight))
    if has_b:
        inputs.append(_t(bias))

    def f(a, *wb, **_attrs):
        # semantic attrs ride the IR record (dispatch passes them back as
        # kwargs); the lowering itself closes over the python values
        dt = a.dtype
        a32 = a.astype(jnp.float32)
        ms = jnp.mean(a32 * a32, axis=axes, keepdims=True)
        y = a32 * jax_rsqrt(ms + epsilon)
        i = 0
        if has_w:
            y = y * wb[i].astype(jnp.float32)
            i += 1
        if has_b:
            y = y + wb[i].astype(jnp.float32)
        return y.astype(dt)
    return dispatch.call("rms_norm", f, inputs,
                         attrs={"epsilon": epsilon,
                                "norm_ndim": x.ndim - axis,
                                "has_w": has_w, "has_b": has_b})


def jax_rsqrt(v):
    return 1.0 / jnp.sqrt(v)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    """Reference semantics (python/paddle/nn/functional/norm.py batch_norm):
    running = momentum*running + (1-momentum)*batch; stats updated in-place."""
    x = _t(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    ch_axis = x.ndim - 1 if channel_last else (1 if x.ndim > 1 else 0)
    red_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch = training and not use_global_stats

    inputs = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        inputs.append(_t(weight))
    if has_b:
        inputs.append(_t(bias))

    if use_batch:
        n = int(np.prod([x.shape[i] for i in red_axes]))

        # Single fused pass: normalize AND emit batch stats as extra outputs
        # (the stats get zero cotangents; the normalization keeps its full
        # mean/var dependence for correct gradients). Mirrors the reference
        # kernel's mean_out/variance_out side outputs.
        def f(a, *wb):
            dt = a.dtype
            a32 = a.astype(jnp.float32)
            mean = jnp.mean(a32, axis=red_axes, keepdims=True)
            var = jnp.var(a32, axis=red_axes, keepdims=True)
            y = (a32 - mean) / jnp.sqrt(var + epsilon)
            y = _affine(y, wb, has_w, has_b, ch_axis, a.ndim)
            return (y.astype(dt), jnp.squeeze(mean, red_axes),
                    jnp.squeeze(var, red_axes))
        out, bm, bv = dispatch.call("batch_norm", f, inputs,
                                    multi_output=True)
        unbiased = bv._data * (n / max(n - 1, 1))
        running_mean.set_value(momentum * running_mean._data.astype(jnp.float32)
                               + (1 - momentum) * bm._data)
        running_var.set_value(momentum * running_var._data.astype(jnp.float32)
                              + (1 - momentum) * unbiased)
        return out

    rm, rv = running_mean._data, running_var._data

    def f(a, *wb):
        dt = a.dtype
        a32 = a.astype(jnp.float32)
        shape = [1] * a.ndim
        shape[ch_axis] = rm.size
        y = ((a32 - rm.astype(jnp.float32).reshape(shape))
             / jnp.sqrt(rv.astype(jnp.float32).reshape(shape) + epsilon))
        y = _affine(y, wb, has_w, has_b, ch_axis, a.ndim)
        return y.astype(dt)
    # the running-stat snapshots force a device->host sync, so they are
    # built only while an export tracer is actually registered
    ea = None
    if dispatch._export_hooks:
        ea = {"epsilon": epsilon, "ch_axis": ch_axis, "has_w": has_w,
              # tpulint: disable=TPU104 — ONNX export attrs are a host interchange boundary
              "mean": np.asarray(rm, np.float32),
              "var": np.asarray(rv, np.float32),  # tpulint: disable=TPU104 — same export boundary
              "has_b": has_b}
    return dispatch.call("batch_norm", f, inputs, export_attrs=ea)


def _affine(y, wb, has_w, has_b, ch_axis, ndim):
    shape = [1] * ndim
    i = 0
    if has_w:
        shape[ch_axis] = wb[i].size
        y = y * wb[i].astype(jnp.float32).reshape(shape)
        i += 1
    if has_b:
        shape[ch_axis] = wb[i].size
        y = y + wb[i].astype(jnp.float32).reshape(shape)
    return y


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    """Normalize channels in ``num_groups`` groups (reference group_norm)."""
    x = _t(x)
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    inputs = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        inputs.append(_t(weight))
    if has_b:
        inputs.append(_t(bias))

    def f(a, *wb):
        dt = a.dtype
        a32 = a.astype(jnp.float32)
        if channel_last:
            perm = (0, a.ndim - 1) + tuple(range(1, a.ndim - 1))
            a32 = jnp.transpose(a32, perm)
        n, c = a32.shape[:2]
        spatial = a32.shape[2:]
        g = a32.reshape((n, num_groups, c // num_groups) + spatial)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        y = ((g - mean) / jnp.sqrt(var + epsilon)).reshape((n, c) + spatial)
        shape = [1, c] + [1] * len(spatial)
        i = 0
        if has_w:
            y = y * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if has_b:
            y = y + wb[i].astype(jnp.float32).reshape(shape)
        if channel_last:
            inv = (0,) + tuple(range(2, a.ndim)) + (1,)
            y = jnp.transpose(y, inv)
        return y.astype(dt)
    return dispatch.call("group_norm", f, inputs)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    """Per-sample, per-channel spatial normalization (reference instance_norm).
    """
    x = _t(x)
    inputs = [x]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        inputs.append(_t(weight))
    if has_b:
        inputs.append(_t(bias))

    def f(a, *wb):
        dt = a.dtype
        a32 = a.astype(jnp.float32)
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a32, axis=axes, keepdims=True)
        var = jnp.var(a32, axis=axes, keepdims=True)
        y = (a32 - mean) / jnp.sqrt(var + eps)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        i = 0
        if has_w:
            y = y * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if has_b:
            y = y + wb[i].astype(jnp.float32).reshape(shape)
        return y.astype(dt)
    return dispatch.call("instance_norm", f, inputs)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    """Lp-normalize along ``axis`` with epsilon floor (reference normalize)."""
    x = _t(x)

    def f(a):
        if p == np.inf:
            n = jnp.max(jnp.abs(a), axis=axis, keepdims=True)
        else:
            n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)
    return dispatch.call("normalize", f, [x])


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    """AlexNet-style cross-channel response normalization (reference
    local_response_norm)."""
    x = _t(x)

    def f(a):
        sq = a * a
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        c = a.shape[ch_axis]
        half = size // 2
        pad_cfg = [(0, 0)] * a.ndim
        pad_cfg[ch_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pad_cfg)
        acc = jnp.zeros_like(a)
        for i in range(size):
            sl = [slice(None)] * a.ndim
            sl[ch_axis] = slice(i, i + c)
            acc = acc + padded[tuple(sl)]
        return a / ((k + alpha * acc) ** beta)
    return dispatch.call("local_response_norm", f, [x])


__all__ = ["layer_norm", "rms_norm", "batch_norm", "group_norm",
           "instance_norm", "normalize", "local_response_norm"]
