"""Attention functionals.

Reference: python/paddle/nn/functional/flash_attention.py (flash_attention
:147, flash_attn_unpadded :455, scaled_dot_product_attention :722) backed by
the third_party/flashattn CUDA library. TPU-native: a Pallas flash-attention
kernel (paddle_tpu/ops/pallas/flash_attention.py) on TPU backends, with an
XLA-fused reference path everywhere else (CPU tests, capture tracing).

Layout follows the reference: q/k/v are (batch, seq, num_heads, head_dim).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core.tensor import Tensor, as_tensor


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _use_pallas(seq_len=None, head_dim=None, dtype=None, causal=True):
    from ...core import flags
    if not flags.get_flag("use_pallas_kernels"):
        return False
    try:
        on_tpu = jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False
    if not on_tpu:
        return False
    if seq_len is None:
        return True
    # algorithm selection (the reference autotune cache's other job,
    # phi/kernels/autotune/cache.h AlgorithmType): when enabled and the
    # user has not pinned flash_min_seq_len, MEASURE XLA-dense vs
    # Pallas-flash for this shape class once per chip and cache the
    # winner. OPT-IN (FLAGS_autotune_attn_impl): unlike tile tuning,
    # a wrong winner here changes the ALGORITHM — a probe taken while
    # the chip transport is degraded can flip a model to the slower
    # path wholesale (observed: a flaky remote-compile window chose
    # dense attention for a d=128 S=2048 model and halved its MFU).
    f = flags._registry.get("flash_min_seq_len")
    if (flags.get_flag("autotune_attn_impl")
            and f is not None and f.value == f.default
            and head_dim is not None):
        from ...ops.pallas import autotune as at
        if at.should_autotune():
            return _tuned_attn_impl(seq_len, head_dim, dtype,
                                    causal) == "pallas"
    if seq_len < flags.get_flag("flash_min_seq_len"):
        # measured crossover (see flag docstring): short sequences run
        # faster through XLA's fused dense attention than the blocked
        # Pallas kernel
        return False
    return True


def _tuned_attn_impl(seq_len, head_dim, dtype, causal):
    """'pallas' or 'xla' for this (seq-bucket, head_dim, causal, dtype)
    class, measured once per chip: one fwd+bwd attention step per
    candidate, chained data-dependently so transport divides out. XLA
    dense at long seq OOMs its (B,H,S,S) logits — the probe's failure
    skips it, which picks pallas exactly where dense is infeasible."""
    from ...ops.pallas import autotune as at

    dt = jnp.dtype(dtype) if dtype is not None else jnp.float32
    sb = at.seq_bucket(seq_len)
    key = at.make_key("attn_impl", s=sb, d=int(head_dim),
                      dt=str(dt), causal=bool(causal))
    cached = at.get_cache().get(key)
    if cached is not None:
        return cached

    B, H = 2, 8
    qs, ks, vs = [], [], []
    for i in range(3):
        kp = jax.random.key(50 + i)
        qs.append(jax.random.normal(
            kp, (B, sb, H, head_dim)).astype(dt))
        ks.append(jax.random.normal(
            jax.random.fold_in(kp, 1), (B, sb, H, head_dim)).astype(dt))
        vs.append(jax.random.normal(
            jax.random.fold_in(kp, 2), (B, sb, H, head_dim)).astype(dt))
    flops = 3 * 4.0 * B * H * sb * sb * head_dim * (0.5 if causal else 1)
    reps = at.probe_reps(flops)
    jitted = {}

    def run(impl, i):
        fn = jitted.get(impl)
        if fn is None:
            def one(q, k, v):
                if impl == "pallas":
                    from ...ops.pallas.flash_attention import \
                        flash_attention_fwd
                    out = flash_attention_fwd(q, k, v, causal=causal)
                else:
                    out = _sdpa_xla(q, k, v, causal=causal)
                return jnp.mean(out.astype(jnp.float32))

            def step(q, k, v):
                def body(_, c):
                    l, g = jax.value_and_grad(one)(c, k, v)
                    # tiny NONZERO factor: a zero coefficient would let
                    # XLA dead-code-eliminate the whole backward pass
                    return c - g * jnp.asarray(1e-30, c.dtype)
                return jax.lax.fori_loop(0, reps, body, q)

            fn = jitted[impl] = jax.jit(step)
        j = i % 3
        return fn(qs[j], ks[j], vs[j])

    default = "pallas" if seq_len >= 1024 else "xla"
    return at.autotune(key, ["pallas", "xla"], run, default,
                       warmup=2, iters=5)


def _sdpa_xla(q, k, v, bias=None, causal=False, dropout_p=0.0, key=None,
              scale=None):
    """Reference-path attention in BSHD layout; fp32 softmax accumulator."""
    sc = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.einsum("bshd,bthd->bhst", q, k) * sc
    logits = qt.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), bool), k=t - s)
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1 - dropout_p), 0.0)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    q, k, v = _t(query), _t(key), _t(value)
    drop_key = None
    if dropout > 0.0 and training:
        from ...core.generator import next_key
        drop_key = next_key()

    if _use_pallas(q.shape[1], q.shape[-1], q.dtype,
                   causal) and dropout == 0.0:
        from ...ops.pallas.flash_attention import flash_attention_fwd

        def f(qa, ka, va):
            return flash_attention_fwd(qa, ka, va, causal=causal)
        out = dispatch.call("flash_attention", f, [q, k, v])
    else:
        def f(qa, ka, va):
            return _sdpa_xla(qa, ka, va, causal=causal,
                             dropout_p=dropout if training else 0.0,
                             key=drop_key)
        out = dispatch.call("flash_attention", f, [q, k, v])
    return out, None


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Fused attention over [B, S, H, D] q/k/v (reference
    nn.functional.scaled_dot_product_attention): softmax(q·kᵀ/√d)·v
    with optional additive/boolean mask, causal masking and dropout.
    Dispatches the Pallas flash kernel when the shape class qualifies,
    else the XLA composite."""
    q, k, v = _t(query), _t(key), _t(value)
    inputs = [q, k, v]
    has_mask = attn_mask is not None
    if has_mask:
        inputs.append(_t(attn_mask))
    drop_key = None
    if dropout_p > 0.0 and training:
        from ...core.generator import next_key
        drop_key = next_key()

    if _use_pallas(q.shape[1], q.shape[-1], q.dtype, is_causal) \
            and not has_mask and dropout_p == 0.0:
        from ...ops.pallas.flash_attention import flash_attention_fwd

        def f(qa, ka, va):
            return flash_attention_fwd(qa, ka, va, causal=is_causal)
        return dispatch.call("scaled_dot_product_attention", f, [q, k, v])

    def f(qa, ka, va, *mask):
        bias = mask[0] if mask else None
        if bias is not None and jnp.issubdtype(bias.dtype, jnp.bool_):
            bias = jnp.where(bias, 0.0, -1e30)
        return _sdpa_xla(qa, ka, va, bias=bias, causal=is_causal,
                         dropout_p=dropout_p if training else 0.0,
                         key=drop_key)
    return dispatch.call("scaled_dot_product_attention", f, inputs,
                         differentiable_mask=[True, True, True]
                         + [False] * has_mask)


# registry entry for the dispatched name: the op already carried a
# named spmd rule + cost model, but no OpDef — the program verifier's
# contract pass (TPU700) surfaced the gap
from ...ops.registry import register as _register  # noqa: E402

_register("scaled_dot_product_attention",
          category="attention")(scaled_dot_product_attention)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen attention over packed (total_tokens, heads, dim) tensors.
    Implemented by segment-masked attention: positions attend only within
    their own sequence (reference flash_attn_unpadded :455)."""
    q, k, v = _t(query), _t(key), _t(value)
    cq, ck = _t(cu_seqlens_q), _t(cu_seqlens_k)

    def f(qa, ka, va, cqa, cka):
        tq = qa.shape[0]
        tk = ka.shape[0]
        # segment id per token from cumulative seqlens
        pos_q = jnp.arange(tq)
        pos_k = jnp.arange(tk)
        seg_q = jnp.searchsorted(cqa[1:], pos_q, side="right")
        seg_k = jnp.searchsorted(cka[1:], pos_k, side="right")
        logits = jnp.einsum("qhd,khd->hqk", qa, ka) * scale
        logits = logits.astype(jnp.float32)
        same = seg_q[:, None] == seg_k[None, :]
        if causal:
            off_q = pos_q - jnp.take(cqa, seg_q)
            off_k = pos_k - jnp.take(cka, seg_k)
            same = same & (off_q[:, None] >= off_k[None, :])
        logits = jnp.where(same[None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(qa.dtype)
        return jnp.einsum("hqk,khd->qhd", probs, va)
    out = dispatch.call("flash_attn_unpadded", f, [q, k, v, cq, ck],
                        differentiable_mask=[True, True, True, False, False])
    return out, None


def flash_attention_with_sparse_mask(query, key, value,
                                     attn_mask_start_row_indices,
                                     attn_mask_start_row=0, dropout_p=0.0,
                                     is_causal=True, training=True, name=None):
    """Sparse-mask attention (reference :844): rows below a per-column start
    index are masked out in addition to the causal structure."""
    q, k, v = _t(query), _t(key), _t(value)
    idx = _t(attn_mask_start_row_indices)

    def f(qa, ka, va, ia):
        sc = 1.0 / math.sqrt(qa.shape[-1])
        logits = jnp.einsum("bshd,bthd->bhst", qa, ka) * sc
        logits = logits.astype(jnp.float32)
        s, t = logits.shape[-2], logits.shape[-1]
        rows = jnp.arange(s)[:, None]
        cols = jnp.arange(t)[None, :]
        mask = rows >= cols if is_causal else jnp.ones((s, t), bool)
        # ia: (batch, num_heads, seq) start row per column
        start = ia[:, :, None, :]
        mask = mask[None, None] & (rows[None, None] < start)
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(qa.dtype)
        return jnp.einsum("bhst,bthd->bshd", probs, va)
    return dispatch.call("flash_attention_with_sparse_mask", f, [q, k, v, idx],
                         differentiable_mask=[True, True, True, False])


def sdp_kernel(*args, **kwargs):
    class _Null:
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False
    return _Null()


__all__ = ["flash_attention", "scaled_dot_product_attention",
           "flash_attn_unpadded", "flash_attention_with_sparse_mask",
           "sdp_kernel"]
