"""Attention functionals.

Reference: python/paddle/nn/functional/flash_attention.py (flash_attention
:147, flash_attn_unpadded :455, scaled_dot_product_attention :722) backed by
the third_party/flashattn CUDA library. TPU-native: a Pallas flash-attention
kernel (paddle_tpu/ops/pallas/flash_attention.py) on TPU backends, with an
XLA-fused reference path everywhere else (CPU tests, capture tracing).

Layout follows the reference: q/k/v are (batch, seq, num_heads, head_dim).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core.tensor import Tensor, as_tensor


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _use_pallas(seq_len=None):
    from ...core import flags
    if not flags.get_flag("use_pallas_kernels"):
        return False
    if seq_len is not None and seq_len < flags.get_flag("flash_min_seq_len"):
        # measured crossover (see flag docstring): short sequences run
        # faster through XLA's fused dense attention than the blocked
        # Pallas kernel
        return False
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _sdpa_xla(q, k, v, bias=None, causal=False, dropout_p=0.0, key=None,
              scale=None):
    """Reference-path attention in BSHD layout; fp32 softmax accumulator."""
    sc = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.einsum("bshd,bthd->bhst", q, k) * sc
    logits = qt.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), bool), k=t - s)
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1 - dropout_p), 0.0)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    q, k, v = _t(query), _t(key), _t(value)
    drop_key = None
    if dropout > 0.0 and training:
        from ...core.generator import next_key
        drop_key = next_key()

    if _use_pallas(q.shape[1]) and dropout == 0.0:
        from ...ops.pallas.flash_attention import flash_attention_fwd

        def f(qa, ka, va):
            return flash_attention_fwd(qa, ka, va, causal=causal)
        out = dispatch.call("flash_attention", f, [q, k, v])
    else:
        def f(qa, ka, va):
            return _sdpa_xla(qa, ka, va, causal=causal,
                             dropout_p=dropout if training else 0.0,
                             key=drop_key)
        out = dispatch.call("flash_attention", f, [q, k, v])
    return out, None


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    q, k, v = _t(query), _t(key), _t(value)
    inputs = [q, k, v]
    has_mask = attn_mask is not None
    if has_mask:
        inputs.append(_t(attn_mask))
    drop_key = None
    if dropout_p > 0.0 and training:
        from ...core.generator import next_key
        drop_key = next_key()

    if _use_pallas(q.shape[1]) and not has_mask and dropout_p == 0.0:
        from ...ops.pallas.flash_attention import flash_attention_fwd

        def f(qa, ka, va):
            return flash_attention_fwd(qa, ka, va, causal=is_causal)
        return dispatch.call("scaled_dot_product_attention", f, [q, k, v])

    def f(qa, ka, va, *mask):
        bias = mask[0] if mask else None
        if bias is not None and jnp.issubdtype(bias.dtype, jnp.bool_):
            bias = jnp.where(bias, 0.0, -1e30)
        return _sdpa_xla(qa, ka, va, bias=bias, causal=is_causal,
                         dropout_p=dropout_p if training else 0.0,
                         key=drop_key)
    return dispatch.call("scaled_dot_product_attention", f, inputs,
                         differentiable_mask=[True, True, True]
                         + [False] * has_mask)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen attention over packed (total_tokens, heads, dim) tensors.
    Implemented by segment-masked attention: positions attend only within
    their own sequence (reference flash_attn_unpadded :455)."""
    q, k, v = _t(query), _t(key), _t(value)
    cq, ck = _t(cu_seqlens_q), _t(cu_seqlens_k)

    def f(qa, ka, va, cqa, cka):
        tq = qa.shape[0]
        tk = ka.shape[0]
        # segment id per token from cumulative seqlens
        pos_q = jnp.arange(tq)
        pos_k = jnp.arange(tk)
        seg_q = jnp.searchsorted(cqa[1:], pos_q, side="right")
        seg_k = jnp.searchsorted(cka[1:], pos_k, side="right")
        logits = jnp.einsum("qhd,khd->hqk", qa, ka) * scale
        logits = logits.astype(jnp.float32)
        same = seg_q[:, None] == seg_k[None, :]
        if causal:
            off_q = pos_q - jnp.take(cqa, seg_q)
            off_k = pos_k - jnp.take(cka, seg_k)
            same = same & (off_q[:, None] >= off_k[None, :])
        logits = jnp.where(same[None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(qa.dtype)
        return jnp.einsum("hqk,khd->qhd", probs, va)
    out = dispatch.call("flash_attn_unpadded", f, [q, k, v, cq, ck],
                        differentiable_mask=[True, True, True, False, False])
    return out, None


def flash_attention_with_sparse_mask(query, key, value,
                                     attn_mask_start_row_indices,
                                     attn_mask_start_row=0, dropout_p=0.0,
                                     is_causal=True, training=True, name=None):
    """Sparse-mask attention (reference :844): rows below a per-column start
    index are masked out in addition to the causal structure."""
    q, k, v = _t(query), _t(key), _t(value)
    idx = _t(attn_mask_start_row_indices)

    def f(qa, ka, va, ia):
        sc = 1.0 / math.sqrt(qa.shape[-1])
        logits = jnp.einsum("bshd,bthd->bhst", qa, ka) * sc
        logits = logits.astype(jnp.float32)
        s, t = logits.shape[-2], logits.shape[-1]
        rows = jnp.arange(s)[:, None]
        cols = jnp.arange(t)[None, :]
        mask = rows >= cols if is_causal else jnp.ones((s, t), bool)
        # ia: (batch, num_heads, seq) start row per column
        start = ia[:, :, None, :]
        mask = mask[None, None] & (rows[None, None] < start)
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(qa.dtype)
        return jnp.einsum("bhst,bthd->bshd", probs, va)
    return dispatch.call("flash_attention_with_sparse_mask", f, [q, k, v, idx],
                         differentiable_mask=[True, True, True, False])


def sdp_kernel(*args, **kwargs):
    class _Null:
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False
    return _Null()


__all__ = ["flash_attention", "scaled_dot_product_attention",
           "flash_attn_unpadded", "flash_attention_with_sparse_mask",
           "sdp_kernel"]
