"""Activation functionals.

Reference: python/paddle/nn/functional/activation.py. All are single jax
lowerings dispatched through the autograd dispatcher; XLA fuses them into
neighbouring matmuls so there is no need for hand-fused kernels here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core.tensor import Tensor, as_tensor


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _unary(name, f, doc):
    def op(x, name=None):
        return dispatch.call(name_, f, [_t(x)])
    name_ = name
    op.__name__ = name
    op.__doc__ = f"{doc} (reference paddle.nn.functional.{name})."
    return op


relu = _unary("relu", lambda a: jnp.maximum(a, 0), "max(x, 0)")
relu6 = _unary("relu6", lambda a: jnp.clip(a, 0, 6), "min(max(x, 0), 6)")
sigmoid = _unary("sigmoid", jax.nn.sigmoid, "1 / (1 + exp(-x))")
tanh = _unary("tanh", jnp.tanh, "Hyperbolic tangent")
silu = _unary("silu", jax.nn.silu, "x * sigmoid(x) — SiLU/swish")
swish = silu
mish = _unary("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)),
              "x * tanh(softplus(x))")
softsign = _unary("softsign", jax.nn.soft_sign, "x / (1 + |x|)")
tanhshrink = _unary("tanhshrink", lambda a: a - jnp.tanh(a), "x - tanh(x)")
log_sigmoid = _unary("log_sigmoid", jax.nn.log_sigmoid,
                     "log(sigmoid(x)), computed stably")


def gelu(x, approximate=False, name=None):
    """Gaussian error linear unit, exact or tanh approximation (reference
    gelu)."""
    # the approximate flag rides the IR record as a semantic attr —
    # compile/fusion folds it into the fused epilogue it rewrites to
    return dispatch.call(
        "gelu",
        lambda a, approximate=approximate: jax.nn.gelu(
            a, approximate=approximate),
        [_t(x)], attrs={"approximate": bool(approximate)})


def leaky_relu(x, negative_slope=0.01, name=None):
    """x if x >= 0 else negative_slope * x (reference leaky_relu)."""
    return dispatch.call(
        "leaky_relu", lambda a: jnp.where(a >= 0, a, negative_slope * a), [_t(x)])


def elu(x, alpha=1.0, name=None):
    """x if x > 0 else alpha * (exp(x) - 1) (reference elu)."""
    return dispatch.call("elu", lambda a: jax.nn.elu(a, alpha=alpha), [_t(x)])


def celu(x, alpha=1.0, name=None):
    """Continuously differentiable ELU: max(0, x) + min(0,
    alpha*(exp(x/alpha)-1))."""
    return dispatch.call("celu", lambda a: jax.nn.celu(a, alpha=alpha), [_t(x)])


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    """Self-normalizing ELU with fixed scale/alpha (reference selu)."""
    return dispatch.call(
        "selu",
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), [_t(x)])


def hardswish(x, name=None):
    """x * relu6(x + 3) / 6 — cheap swish approximation (reference hardswish).
    """
    return dispatch.call("hardswish", lambda a: a * jnp.clip(a + 3, 0, 6) / 6, [_t(x)])


def hardsigmoid(x, slope=1.0 / 6, offset=0.5, name=None):
    """Piecewise-linear sigmoid approximation (reference hardsigmoid)."""
    return dispatch.call(
        "hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0, 1), [_t(x)])


def hardtanh(x, min=-1.0, max=1.0, name=None):
    """Clip x to [min, max] (reference hardtanh)."""
    return dispatch.call("hardtanh", lambda a: jnp.clip(a, min, max), [_t(x)])


def hardshrink(x, threshold=0.5, name=None):
    """x where |x| > threshold else 0 (reference hardshrink)."""
    return dispatch.call(
        "hardshrink", lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), [_t(x)])


def softshrink(x, threshold=0.5, name=None):
    """Shrink x toward 0 by threshold, 0 inside the band (reference
    softshrink)."""
    return dispatch.call(
        "softshrink",
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)), [_t(x)])


def softplus(x, beta=1.0, threshold=20.0, name=None):
    """log(1 + exp(beta*x)) / beta with linear tail (reference softplus)."""
    def f(a):
        scaled = beta * a
        return jnp.where(scaled > threshold, a, jax.nn.softplus(scaled) / beta)
    return dispatch.call("softplus", f, [_t(x)])


def prelu(x, weight, data_format="NCHW", name=None):
    """Leaky relu with LEARNED per-channel slope ``weight`` (reference prelu).
    """
    x, w = _t(x), _t(weight)

    def f(a, wa):
        if wa.size == 1:
            wb = wa.reshape(())
        else:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format in ("NCHW", "NCL", "NCDHW") else a.ndim - 1
            shape[ch_axis] = wa.size
            wb = wa.reshape(shape)
        return jnp.where(a >= 0, a, wb * a)
    return dispatch.call("prelu", f, [x, w])


def rrelu(x, lower=0.125, upper=1.0 / 3, training=False, name=None):
    """Randomized leaky relu: slope sampled in [lower, upper] at train time
    (reference rrelu)."""
    from ...core.generator import next_key
    x = _t(x)
    if training:
        key = next_key()

        def f(a):
            slope = jax.random.uniform(key, a.shape, dtype=a.dtype,
                                       minval=lower, maxval=upper)
            return jnp.where(a >= 0, a, slope * a)
    else:
        mid = (lower + upper) / 2

        def f(a):
            return jnp.where(a >= 0, a, mid * a)
    return dispatch.call("rrelu", f, [x])


def softmax(x, axis=-1, dtype=None, name=None):
    """exp(x)/sum(exp(x)) along ``axis``, max-subtracted for stability
    (reference softmax)."""
    x = _t(x)

    def f(a):
        if dtype is not None:
            from ...core.dtype import convert_dtype
            a = a.astype(convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return dispatch.call("softmax", f, [x], export_attrs={"axis": axis})


def log_softmax(x, axis=-1, dtype=None, name=None):
    """x - logsumexp(x) along ``axis`` (reference log_softmax)."""
    x = _t(x)

    def f(a):
        if dtype is not None:
            from ...core.dtype import convert_dtype
            a = a.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return dispatch.call("log_softmax", f, [x])


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    """Differentiable categorical relaxation; straight-through when hard=True
    (reference gumbel_softmax)."""
    from ...core.generator import next_key
    x = _t(x)
    key = next_key()

    def f(a):
        g = jax.random.gumbel(key, a.shape, dtype=a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis,
                                        inplace=False)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y
    return dispatch.call("gumbel_softmax", f, [x])


def maxout(x, groups, axis=1, name=None):
    """Max over ``groups`` channel partitions (reference maxout)."""
    x = _t(x)

    def f(a):
        shape = list(a.shape)
        ax = axis if axis >= 0 else a.ndim + axis
        c = shape[ax]
        new_shape = shape[:ax] + [c // groups, groups] + shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return dispatch.call("maxout", f, [x])


def glu(x, axis=-1, name=None):
    """Gated linear unit: a * sigmoid(b) over a channel split (reference glu).
    """
    x = _t(x)

    def f(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return dispatch.call("glu", f, [x])


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    """x where x > threshold else value (reference thresholded_relu)."""
    return dispatch.call(
        "thresholded_relu", lambda a: jnp.where(a > threshold, a, value), [_t(x)])


__all__ = [
    "relu", "relu6", "sigmoid", "tanh", "silu", "swish", "mish", "softsign",
    "tanhshrink", "log_sigmoid", "gelu", "leaky_relu", "elu", "celu", "selu",
    "hardswish", "hardsigmoid", "hardtanh", "hardshrink", "softshrink",
    "softplus", "prelu", "rrelu", "softmax", "log_softmax", "gumbel_softmax",
    "maxout", "glu", "thresholded_relu", "swiglu",
]


def swiglu(x, y=None, name=None):
    """SwiGLU: silu(x) * y; single-arg form splits x in half on the last
    dim (reference ops.yaml swiglu, used by LLaMA MLPs)."""
    from ...core import dispatch as _dispatch
    if y is None:
        def f(a):
            u, v = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(u) * v
        return _dispatch.call("swiglu", f, [_t(x)])
    return _dispatch.call("swiglu",
                          lambda a, b: jax.nn.silu(a) * b, [_t(x), _t(y)])
