"""First-class fused ops — the rewrite targets of the graph-fusion pass.

Capability parity with the reference's ``fused_ops.yaml`` hot set
(reference: paddle/phi/kernels/fusion/ — fused_bias_act,
fused_layernorm/fused_rms_norm [residual in-pass], fused_rope). Each op
here is ONE registered OpDef (category ``fusion``) with two
implementations:

* **xla** — the fused jnp composite: numerically identical to the
  unfused op chain it replaces (XLA fuses the expression either way),
  portable to every backend. This is the reference implementation the
  Pallas path's backward recomputes through, and the "unfused" leg of
  the autotune comparison.
* **pallas** — the hand-tiled TPU kernel (:mod:`...ops.pallas.fused_ops`)
  that collapses the chain's HBM round-trips into one pass.

Implementation choice is a per-shape-class measured decision through
the round-5 autotuner (``ops/pallas/autotune.py``): the candidate grid
is ``["xla", ("pallas", tile…)…]`` so one cached winner encodes both
the implementation and its tile sizes. Off-TPU (or with
``FLAGS_use_autotune=0``) the composite is the default; tests force the
kernel path by flipping ``fused_ops.INTERPRET``.

Gradients: the Pallas forwards carry a ``jax.custom_vjp`` whose
backward is ``jax.vjp`` of the composite (FA2-style recompute) — so
eager, to_static, and fused-pass gradients agree with the unfused chain
to float tolerance by construction.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...core import dispatch, flags
from ...core.tensor import Tensor, as_tensor
from ...ops.registry import register

__all__ = ["fused_bias_act", "fused_residual_norm", "fused_norm_linear",
           "fused_rope_proj", "FUSED_OPS", "ACTIVATIONS"]

#: the closed fused-op vocabulary (tools/fusion_audit.py pivots on this)
FUSED_OPS = ("fused_bias_act", "fused_residual_norm",
             "fused_norm_linear", "fused_rope_proj")

ACTIVATIONS = ("gelu", "gelu_tanh", "silu", "relu")


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _act(y, activation: str):
    """Single activation vocabulary shared with the Pallas kernels —
    one implementation, so composite and kernel can never disagree on
    what an activation name means."""
    from ...ops.pallas.fused_ops import _act_apply
    return _act_apply(y, activation)


def _norm32(a32, w32, b32, norm_type: str, eps: float):
    """fp32 row-norm matching nn.functional.norm exactly (bit-for-bit
    numerics parity with the unfused chain is the rewrite contract)."""
    if norm_type == "rms_norm":
        ms = jnp.mean(a32 * a32, axis=-1, keepdims=True)
        y = a32 / jnp.sqrt(ms + eps)
    else:
        mean = jnp.mean(a32, axis=-1, keepdims=True)
        var = jnp.var(a32, axis=-1, keepdims=True)
        y = (a32 - mean) / jnp.sqrt(var + eps)
    if w32 is not None:
        y = y * w32
    if b32 is not None:
        y = y + b32
    return y


# --------------------------------------------------------------------------
# Implementation selection (round-5 autotuner reuse)
# --------------------------------------------------------------------------
def _pallas_forced() -> bool:
    """CPU tests flip fused_ops.INTERPRET to exercise the kernel path."""
    from ...ops.pallas import fused_ops as FK
    return FK.INTERPRET


def _choose_impl(kind: str, key_attrs: dict, tile_candidates,
                 make_run, default_tile):
    """Measured winner for this shape class: ``"xla"`` or
    ``("pallas", *tile)``. ``make_run(cand)`` returns a nullary jitted
    probe executor; measurement happens once per (key, chip) and
    persists via the autotune cache."""
    from ...ops.pallas import autotune as at

    if not flags.get_flag("use_pallas_kernels"):
        return "xla"
    if _pallas_forced():
        return ("pallas",) + tuple(default_tile)
    if not at.is_tpu_backend():
        return "xla"
    if not at.should_autotune():
        # real TPU, autotune off: hand-tuned default tiles
        return ("pallas",) + tuple(default_tile)
    key = at.make_key(f"fused_{kind}", **key_attrs)
    cached = at.get_cache().get(key)
    if cached is not None:
        return tuple(cached) if isinstance(cached, list) else cached
    candidates = ["xla"] + [("pallas",) + tuple(t)
                            for t in tile_candidates]
    jitted = {}

    def run(cand, i):
        c_key = repr(cand)
        fn = jitted.get(c_key)
        if fn is None:
            fn = jitted[c_key] = make_run(cand)
        return fn(i)

    won = at.autotune(key, candidates, run, "xla")
    return tuple(won) if isinstance(won, list) else won


def _with_composite_vjp(pallas_fwd, composite):
    """Pallas forward + composite-recompute backward (the fused kernels
    have no hand-written backward; recompute through the numerics
    reference keeps gradient parity by construction)."""

    @jax.custom_vjp
    def op(*args):
        return pallas_fwd(*args)

    def fwd(*args):
        return pallas_fwd(*args), args

    def bwd(res, g):
        return jax.vjp(composite, *res)[1](g)

    op.defvjp(fwd, bwd)
    return op


def _probe_arrays(shapes, dtype, nvar=3):
    """Distinct random probe inputs (replay-caching backends fake
    repeat-identical executions; see autotune docstring)."""
    outs = []
    for i in range(nvar):
        key = jax.random.key(i)
        outs.append([jax.random.normal(jax.random.fold_in(key, j),
                                       s).astype(dtype)
                     for j, s in enumerate(shapes)])
    return outs


# --------------------------------------------------------------------------
# Lowering factories — shared by the eager functionals and the fusion
# pass (the pass binds these as the fused _OpRecord lowerings)
# --------------------------------------------------------------------------
def bias_act_lowering(activation: str):
    def f(x, b, activation=activation):
        def composite(x, b):
            # natural jnp promotion — the unfused chain is add(x, b)
            # (jnp.add) then act, so mixed-dtype inputs must promote
            # identically, not cast down to x.dtype
            return _act(x + b, activation)

        impl = _choose_bias_act_impl(x.shape, b.shape, x.dtype,
                                     activation)
        if impl == "xla" or b.dtype != x.dtype:
            # mixed dtypes take the composite: the Pallas path computes
            # in x.dtype, which would silently change the output dtype
            return composite(x, b)
        from ...ops.pallas import fused_ops as FK
        rows = int(_numel(x.shape[:-1]))

        def pallas_fwd(x, b, _t=impl[1:]):
            y = FK.fused_bias_act(x.reshape(rows, x.shape[-1]),
                                  b.astype(x.dtype), act=activation,
                                  block_rows=_t[0])
            return y.reshape(x.shape)

        return _with_composite_vjp(pallas_fwd, composite)(x, b)
    return f


def _numel(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _choose_bias_act_impl(x_shape, b_shape, dtype, activation):
    from ...ops.pallas import autotune as at

    rows, d = _numel(x_shape[:-1]), int(x_shape[-1])
    if d % 128 or rows < 8:
        return "xla"

    def make_run(cand):
        from ...ops.pallas import fused_ops as FK
        probes = _probe_arrays([(min(at.seq_bucket(rows), 4096), d),
                                (d,)], dtype)

        if cand == "xla":
            fn = jax.jit(lambda x, b: _act(x + b, activation))
        else:
            fn = jax.jit(functools.partial(
                FK.fused_bias_act, act=activation, block_rows=cand[1]))
        return lambda i, _f=fn, _p=probes: _f(*_p[i % len(_p)])

    from ...ops.pallas import fused_ops as FK
    return _choose_impl(
        "bias_act", dict(r=at.seq_bucket(rows), d=d, dt=str(dtype),
                         act=activation),
        [(r,) for r in FK.NORM_ROW_CANDIDATES], make_run,
        (FK.DEFAULT_NORM_ROWS,))


def residual_norm_lowering(norm_type: str, epsilon: float, has_w: bool,
                           has_b: bool):
    def f(x, res, *wb, norm_type=norm_type, epsilon=epsilon):
        def composite(x, res, *wb):
            # s promotes like the unfused add(x, res); the norm returns
            # s.dtype like the unfused layer_norm/rms_norm lowering
            s = x + res
            i = 0
            w32 = wb[i].astype(jnp.float32) if has_w else None
            i += has_w
            b32 = wb[i].astype(jnp.float32) if has_b else None
            y = _norm32(s.astype(jnp.float32), w32, b32, norm_type,
                        epsilon)
            return y.astype(s.dtype), s

        d = int(x.shape[-1])
        impl = _choose_norm_impl("residual_norm", x.shape, x.dtype,
                                 norm_type)
        if impl == "xla" or res.dtype != x.dtype:
            # mixed dtypes take the composite (Pallas computes in
            # x.dtype and would change the outputs' dtype)
            return composite(x, res, *wb)
        from ...ops.pallas import fused_ops as FK
        rows = _numel(x.shape[:-1])

        def pallas_fwd(x, res, *wb, _t=impl[1:]):
            i = 0
            w = wb[i].astype(x.dtype) if has_w else jnp.ones(
                (d,), x.dtype)
            i += has_w
            b = wb[i].astype(x.dtype) if has_b else jnp.zeros(
                (d,), x.dtype)
            y, s = FK.fused_residual_norm(
                x.reshape(rows, d), res.reshape(rows, d), w, b,
                kind=norm_type, eps=epsilon, block_rows=_t[0])
            return y.reshape(x.shape), s.reshape(x.shape)

        return _with_composite_vjp(pallas_fwd, composite)(x, res, *wb)
    return f


def _choose_norm_impl(kind, x_shape, dtype, norm_type):
    from ...ops.pallas import autotune as at
    from ...ops.pallas import fused_ops as FK

    rows, d = _numel(x_shape[:-1]), int(x_shape[-1])
    if not FK.pallas_ok_norm(rows, d):
        return "xla"

    def make_run(cand):
        pr = min(at.seq_bucket(rows), 4096)
        probes = _probe_arrays([(pr, d), (pr, d), (d,), (d,)], dtype)
        if cand == "xla":
            def xf(x, r, w, b):
                s = x + r
                return _norm32(s.astype(jnp.float32),
                               w.astype(jnp.float32),
                               b.astype(jnp.float32), norm_type,
                               1e-5).astype(x.dtype), s
            fn = jax.jit(xf)
        else:
            fn = jax.jit(functools.partial(
                FK.fused_residual_norm, kind=norm_type, eps=1e-5,
                block_rows=cand[1]))
        return lambda i, _f=fn, _p=probes: _f(*_p[i % len(_p)])

    return _choose_impl(
        kind, dict(r=at.seq_bucket(rows), d=d, dt=str(dtype),
                   nt=norm_type),
        [(r,) for r in FK.NORM_ROW_CANDIDATES], make_run,
        (FK.DEFAULT_NORM_ROWS,))


def norm_linear_lowering(norm_type: str, epsilon: float,
                         activation: str, has_bias: bool, has_nw: bool,
                         has_nb: bool):
    """x(…, K) [+norm params] @ W(K, N) [+bias] [+act] as one op.
    Input order: (x, weight[, bias][, norm_weight][, norm_bias])."""
    def f(x, w, *rest, norm_type=norm_type, epsilon=epsilon,
          activation=activation):
        i = 0
        b = rest[i] if has_bias else None
        i += has_bias
        nw = rest[i] if has_nw else None
        i += has_nw
        nb = rest[i] if has_nb else None

        def composite(x, w, *rest):
            i = 0
            b = rest[i] if has_bias else None
            i += has_bias
            nw = rest[i] if has_nw else None
            i += has_nw
            nb = rest[i] if has_nb else None
            xn = x
            if norm_type:
                xn = _norm32(
                    x.astype(jnp.float32),
                    nw.astype(jnp.float32) if nw is not None else None,
                    nb.astype(jnp.float32) if nb is not None else None,
                    norm_type, epsilon).astype(x.dtype)
            y = jnp.matmul(xn, w.astype(xn.dtype))
            if b is not None:
                y = y + b.astype(y.dtype)
            return _act(y, activation)

        k = int(x.shape[-1])
        n = int(w.shape[-1])
        impl = _choose_norm_linear_impl(x.shape, k, n, x.dtype,
                                        norm_type, activation)
        if impl == "xla":
            return composite(x, w, *rest)
        from ...ops.pallas import fused_ops as FK
        rows = _numel(x.shape[:-1])

        def pallas_fwd(x, w, *rest, _t=impl[1:]):
            i = 0
            b = rest[i] if has_bias else None
            i += has_bias
            nw = rest[i] if has_nw else None
            i += has_nw
            nb = rest[i] if has_nb else None
            y = FK.fused_matmul(
                x.reshape(rows, k), w.astype(x.dtype),
                b.astype(x.dtype) if b is not None else None,
                nw.astype(x.dtype) if nw is not None else None,
                nb.astype(x.dtype) if nb is not None else None,
                norm_kind=norm_type, act=activation, eps=epsilon,
                block_m=_t[0], block_n=_t[1])
            return y.reshape(x.shape[:-1] + (n,))

        return _with_composite_vjp(pallas_fwd, composite)(x, w, *rest)
    return f


def _choose_norm_linear_impl(x_shape, k, n, dtype, norm_type,
                             activation):
    from ...ops.pallas import autotune as at
    from ...ops.pallas import fused_ops as FK

    rows = _numel(x_shape[:-1])
    bm, bn = FK.DEFAULT_BLOCK_M, FK.DEFAULT_BLOCK_N
    bm = max(8, min(bm, max(rows, 8)))
    bn = min(bn, n)
    if not FK.pallas_ok_matmul(rows, k, n, bm, bn):
        return "xla"

    def make_run(cand):
        pr = min(at.seq_bucket(rows), 2048)
        probes = _probe_arrays([(pr, k), (k, n), (n,), (k,), (k,)],
                               dtype)
        if cand == "xla":
            def xf(x, w, b, nw, nb):
                xn = _norm32(x.astype(jnp.float32),
                             nw.astype(jnp.float32),
                             nb.astype(jnp.float32),
                             norm_type or "layer_norm",
                             1e-5).astype(x.dtype) if norm_type else x
                return _act(jnp.matmul(xn, w) + b, activation)
            fn = jax.jit(xf)
        else:
            fn = jax.jit(functools.partial(
                FK.fused_matmul, norm_kind=norm_type, act=activation,
                eps=1e-5, block_m=cand[1], block_n=cand[2]))
        return lambda i, _f=fn, _p=probes: _f(*_p[i % len(_p)])

    tiles = [t for t in FK.MATMUL_TILE_CANDIDATES
             if FK.pallas_ok_matmul(rows, k, n, min(t[0], max(rows, 8)),
                                    min(t[1], n))]
    return _choose_impl(
        "norm_linear", dict(r=at.seq_bucket(rows), k=k, n=n,
                            dt=str(dtype), nt=norm_type or "",
                            act=activation or ""),
        tiles or [(bm, bn)], make_run, (bm, bn))


def rope_proj_lowering(num_heads: int, theta: float, pos_offset: int,
                       has_bias: bool):
    """x(B, S, K) @ W(K, H*D) → rope-rotated (B, S, H, D)."""
    def f(x, w, *rest, num_heads=num_heads, theta=theta,
          pos_offset=pos_offset):
        b = rest[0] if has_bias else None
        n = int(w.shape[-1])
        head_dim = n // num_heads

        def composite(x, w, *rest):
            from ...models.llama import rope_rotate
            b = rest[0] if has_bias else None
            y = jnp.matmul(x, w.astype(x.dtype))
            if b is not None:
                y = y + b.astype(y.dtype)
            bt, s = int(x.shape[0]), int(x.shape[1])
            a = y.reshape(bt, s, num_heads, head_dim)
            return rope_rotate(a, theta, pos_offset)

        impl = _choose_rope_impl(x.shape, n, head_dim, x.dtype, theta)
        if impl == "xla":
            return composite(x, w, *rest)
        from ...ops.pallas import fused_ops as FK
        bt, s, k = (int(d) for d in x.shape)

        def pallas_fwd(x, w, *rest, _t=impl[1:]):
            b = rest[0] if has_bias else None
            y = FK.fused_matmul_rope(
                x.reshape(bt * s, k), w.astype(x.dtype),
                b.astype(x.dtype) if b is not None else None,
                seq=s, head_dim=head_dim, theta=theta,
                pos_offset=pos_offset, block_m=_t[0], block_n=_t[1])
            return y.reshape(bt, s, num_heads, head_dim)

        return _with_composite_vjp(pallas_fwd, composite)(x, w, *rest)
    return f


def _choose_rope_impl(x_shape, n, head_dim, dtype, theta):
    from ...ops.pallas import autotune as at
    from ...ops.pallas import fused_ops as FK

    if len(x_shape) != 3:
        return "xla"
    rows, k = _numel(x_shape[:-1]), int(x_shape[-1])
    bm, bn = FK.DEFAULT_BLOCK_M, FK.DEFAULT_BLOCK_N
    bm = max(8, min(bm, max(rows, 8)))
    bn = min(bn, n)
    if bn % head_dim:
        bn = (bn // head_dim or 1) * head_dim
    if not FK.pallas_ok_matmul_rope(rows, k, n, head_dim, bm, bn):
        return "xla"
    # tile grid filtered to rope-legal candidates
    tiles = [t for t in FK.MATMUL_TILE_CANDIDATES
             if FK.pallas_ok_matmul_rope(
                 rows, k, n, head_dim, min(t[0], max(rows, 8)),
                 min(t[1], n))]

    def make_run(cand):
        from ...models.llama import rope_rotate
        s_b = min(at.seq_bucket(int(x_shape[1])), 2048)
        probes = _probe_arrays([(2, s_b, k), (k, n)], dtype)
        if cand == "xla":
            heads = n // head_dim

            def xf(x, w):
                y = jnp.matmul(x, w)
                a = y.reshape(x.shape[0], x.shape[1], heads, head_dim)
                return rope_rotate(a, theta, 0)
            fn = jax.jit(xf)
        else:
            def pf(x, w, _c=cand):
                return FK.fused_matmul_rope(
                    x.reshape(-1, k), w, None, seq=x.shape[1],
                    head_dim=head_dim, theta=theta, pos_offset=0,
                    block_m=_c[1], block_n=_c[2])
            fn = jax.jit(pf)
        return lambda i, _f=fn, _p=probes: _f(*_p[i % len(_p)])

    return _choose_impl(
        "rope_proj", dict(r=at.seq_bucket(rows), k=k, n=n,
                          hd=head_dim, dt=str(dtype)),
        tiles or [(bm, bn)], make_run, (bm, bn))


# --------------------------------------------------------------------------
# Public functionals (registered OpDefs, category "fusion")
# --------------------------------------------------------------------------
@register("fused_bias_act", "fusion")
def fused_bias_act(x, bias, activation="gelu", name=None):
    """act(x + bias) as ONE op (reference fused_bias_act): bias add and
    activation share a single VPU pass / XLA fusion instead of two HBM
    round-trips. ``activation``: gelu | gelu_tanh | silu | relu."""
    x = _t(x)
    return dispatch.call("fused_bias_act",
                         bias_act_lowering(activation), [x, _t(bias)],
                         attrs=None,
                         export_attrs={"activation": activation})


@register("fused_residual_norm", "fusion")
def fused_residual_norm(x, residual, weight=None, bias=None,
                        norm_type="layer_norm", epsilon=1e-5,
                        name=None):
    """(normed, summed) = norm(x + residual), x + residual — the
    residual-add + layernorm/rms_norm pair fused into one pass
    (reference fused_layernorm's residual input). The sum is a REAL
    output so the residual stream keeps flowing without recompute."""
    x = _t(x)
    inputs = [x, _t(residual)]
    has_w, has_b = weight is not None, bias is not None
    if has_w:
        inputs.append(_t(weight))
    if has_b:
        inputs.append(_t(bias))
    return dispatch.call(
        "fused_residual_norm",
        residual_norm_lowering(norm_type, epsilon, has_w, has_b),
        inputs, multi_output=True,
        export_attrs={"norm_type": norm_type, "epsilon": epsilon})


@register("fused_norm_linear", "fusion")
def fused_norm_linear(x, weight, bias=None, norm_weight=None,
                      norm_bias=None, activation="",
                      norm_type="layer_norm", epsilon=1e-5, name=None):
    """act(norm(x) @ W + b) as ONE op — the layernorm/rms_norm → linear
    → bias → activation chain (reference fused_bias_act +
    fused_layernorm around a GEMM). ``norm_type=''`` skips the norm
    (plain linear+bias+act); ``activation=''`` skips the epilogue."""
    x = _t(x)
    inputs = [x, _t(weight)]
    has_bias = bias is not None
    has_nw = norm_weight is not None
    has_nb = norm_bias is not None
    if has_bias:
        inputs.append(_t(bias))
    if has_nw:
        inputs.append(_t(norm_weight))
    if has_nb:
        inputs.append(_t(norm_bias))
    return dispatch.call(
        "fused_norm_linear",
        norm_linear_lowering(norm_type, epsilon, activation, has_bias,
                             has_nw, has_nb),
        inputs,
        export_attrs={"norm_type": norm_type, "activation": activation,
                      "epsilon": epsilon})


@register("fused_rope_proj", "fusion")
def fused_rope_proj(x, weight, bias=None, num_heads=1, theta=10000.0,
                    pos_offset=0, name=None):
    """rope(reshape(x @ W + b, heads)) as ONE op (reference fused_rope
    applied to the QKV projection): the projection lands in HBM already
    split into heads and rotary-rotated. ``pos_offset`` must be a
    python int (decode-time traced offsets stay on the unfused path)."""
    x = _t(x)
    inputs = [x, _t(weight)]
    has_bias = bias is not None
    if has_bias:
        inputs.append(_t(bias))
    return dispatch.call(
        "fused_rope_proj",
        rope_proj_lowering(int(num_heads), float(theta),
                           int(pos_offset), has_bias),
        inputs,
        export_attrs={"num_heads": num_heads, "theta": theta,
                      "pos_offset": pos_offset})
