"""Common functionals: linear, dropout, embedding, pad, interpolate, etc.

Reference: python/paddle/nn/functional/common.py, input.py.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dispatch
from ...core.generator import next_key
from ...core.tensor import Tensor, as_tensor


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b; W is (in, out) per the reference layout
    (python/paddle/nn/functional/common.py linear)."""
    inputs = [_t(x), _t(weight)]
    if bias is not None:
        inputs.append(_t(bias))

        def f(a, w, b):
            return jnp.matmul(a, w.astype(a.dtype)) + b.astype(a.dtype)
    else:
        def f(a, w):
            return jnp.matmul(a, w.astype(a.dtype))
    return dispatch.call("linear", f, inputs)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    """Zero elements with probability p at train time, rescaling survivors
    (reference dropout)."""
    x = _t(x)
    if not training or p == 0:
        if mode == "downscale_in_infer" and not training:
            return dispatch.call("dropout_scale", lambda a: a * (1 - p), [x])
        return x
    if p == 1:
        return dispatch.call("dropout", lambda a: jnp.zeros_like(a), [x])
    key = next_key()

    def f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1 - p, tuple(shape))
        y = jnp.where(keep, a, 0.0)
        if mode == "upscale_in_train":
            y = y / (1 - p)
        return y
    return dispatch.call("dropout", f, [x])


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    """Channel-wise dropout over NCHW feature maps (reference dropout2d)."""
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    """Channel-wise dropout over NCDHW feature maps (reference dropout3d)."""
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    """SELU-preserving dropout: dropped units take the negative saturation
    value (reference alpha_dropout)."""
    x = _t(x)
    if not training or p == 0:
        return x
    key = next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(a):
        keep = jax.random.bernoulli(key, 1 - p, a.shape)
        coef_a = ((1 - p) * (1 + p * alpha_p ** 2)) ** -0.5
        coef_b = -coef_a * p * alpha_p
        return coef_a * jnp.where(keep, a, alpha_p) + coef_b
    return dispatch.call("alpha_dropout", f, [x])


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Row gather from ``weight`` by integer ids, optional padding_idx zero-
    grad (reference embedding)."""
    x, w = _t(x), _t(weight)

    def f(ids, table):
        out = jnp.take(table, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return dispatch.call("embedding", f, [x, w],
                         differentiable_mask=[False, True])


def embedding_bag(x, weight, mode="sum", padding_idx=None, name=None):
    """Pooled row gather: ids ``(..., L)`` x table ``(V, H)`` ->
    ``(..., H)``, reduced over the bag dim ``L`` (reference
    embedding_bag; the DLRM multi-hot lookup shape).

    ``padding_idx`` rows contribute zero to the pool; ``mode="mean"``
    divides by the count of non-padding ids per bag (a bag of only
    padding ids pools to zero). The op traces as ``embedding_bag`` so
    the planner prices it and the spmd rule marks the output
    reduce-pending over a vocab-sharded table's axes (see
    ``distributed/spmd/rules.py:embedding_bag_rule``).
    """
    if mode not in ("sum", "mean"):
        raise ValueError(f"embedding_bag: mode must be sum|mean, "
                         f"got {mode!r}")
    x, w = _t(x), _t(weight)

    def f(ids, table):
        ids32 = ids.astype(jnp.int32)
        rows = jnp.take(table, ids32, axis=0)
        if padding_idx is not None:
            keep = (ids32 != padding_idx)[..., None]
            rows = jnp.where(keep, rows, 0.0)
            denom = jnp.maximum(
                jnp.sum(keep, axis=-2).astype(rows.dtype), 1.0)
        else:
            denom = jnp.asarray(float(ids32.shape[-1]), rows.dtype)
        pooled = jnp.sum(rows, axis=-2)
        if mode == "mean":
            pooled = pooled / denom
        return pooled
    return dispatch.call("embedding_bag", f, [x, w],
                         differentiable_mask=[False, True])


def one_hot(x, num_classes, name=None):
    return dispatch.call(
        "one_hot",
        lambda a: jax.nn.one_hot(a.astype(jnp.int32), num_classes,
                                 dtype=jnp.float32),
        [_t(x)], differentiable_mask=[False])


_PAD_MODES = {"constant": "constant", "reflect": "reflect",
              "replicate": "edge", "circular": "wrap"}


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = _t(x)
    if isinstance(pad, Tensor):
        pad = [int(v) for v in pad.numpy().tolist()]
    pad = list(pad)

    def f(a):
        nd = a.ndim
        if len(pad) == 2 * nd:
            cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle semantics: pad applies to spatial dims, ordered last-first
            nspatial = len(pad) // 2
            cfg = [(0, 0)] * nd
            channel_last = data_format.endswith("C") and data_format != "NC"
            spatial_start = 1 if channel_last else 2
            for i in range(nspatial):
                dim = spatial_start + (nspatial - 1 - i)
                cfg[dim] = (pad[2 * i], pad[2 * i + 1])
        if mode == "constant":
            return jnp.pad(a, cfg, constant_values=value)
        return jnp.pad(a, cfg, mode=_PAD_MODES[mode])
    return dispatch.call("pad", f, [x])


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Zero-pad the two spatial dims of NCHW input (reference zeropad2d)."""
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    """Resize spatial dims by nearest/bilinear/bicubic/area/trilinear
    (reference interpolate)."""
    x = _t(x)
    channel_last = data_format in ("NHWC", "NWC", "NDHWC")
    nd = x.ndim - 2
    spatial = x.shape[1:-1] if channel_last else x.shape[2:]
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(v) for v in size.numpy().tolist()]
        out_size = [int(s) for s in (size if isinstance(size, (list, tuple))
                                     else [size] * nd)]
    else:
        sf = (scale_factor if isinstance(scale_factor, (list, tuple))
              else [scale_factor] * nd)
        out_size = [int(spatial[i] * float(sf[i])) for i in range(nd)]

    method = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
              "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def f(a):
        if channel_last:
            shape = (a.shape[0],) + tuple(out_size) + (a.shape[-1],)
        else:
            shape = a.shape[:2] + tuple(out_size)
        if method == "nearest":
            return jax.image.resize(a, shape, method="nearest")
        if align_corners:
            # jax.image.resize has no align_corners; emulate with explicit
            # coordinate map (reference interpolate align_corners=True).
            y = a
            axes = list(range(1, 1 + nd)) if channel_last else list(range(2, 2 + nd))
            for i, ax in enumerate(axes):
                in_sz, out_sz = y.shape[ax], out_size[i]
                if in_sz == out_sz:
                    continue
                pos = (jnp.arange(out_sz) * (in_sz - 1) / max(out_sz - 1, 1))
                lo = jnp.floor(pos).astype(jnp.int32)
                hi = jnp.minimum(lo + 1, in_sz - 1)
                w = (pos - lo).astype(a.dtype)
                y_lo = jnp.take(y, lo, axis=ax)
                y_hi = jnp.take(y, hi, axis=ax)
                bshape = [1] * y.ndim
                bshape[ax] = out_sz
                w = w.reshape(bshape)
                y = y_lo * (1 - w) + y_hi * w
            return y
        return jax.image.resize(a, shape, method=method)
    return dispatch.call("interpolate", f, [x])


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    """Alias of interpolate (reference upsample)."""
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: python/paddle/nn/functional/common.py unfold)."""
    x = _t(x)
    k = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    s = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    d = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    p = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]

    def f(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])])
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=tuple(k), window_strides=tuple(s),
            padding=[(0, 0), (0, 0)], rhs_dilation=tuple(d),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: (N, C*kh*kw, out_h, out_w) -> (N, C*kh*kw, L)
        return patches.reshape(n, patches.shape[1], -1)
    return dispatch.call("unfold", f, [x])


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """Combine sliding local blocks back into a spatial tensor — inverse of
    unfold (reference fold)."""
    x = _t(x)
    out = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    k = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    s = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    d = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    p = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]

    def f(a):
        n, ckk, L = a.shape
        c = ckk // (k[0] * k[1])
        ph = out[0] + p[0] + p[2]
        pw = out[1] + p[1] + p[3]
        oh = (ph - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (pw - d[1] * (k[1] - 1) - 1) // s[1] + 1
        a = a.reshape(n, c, k[0], k[1], oh, ow)
        result = jnp.zeros((n, c, ph, pw), dtype=a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                hi = i * d[0]
                wj = j * d[1]
                result = result.at[:, :, hi:hi + oh * s[0]:s[0],
                                   wj:wj + ow * s[1]:s[1]].add(a[:, :, i, j])
        return result[:, :, p[0]:ph - p[2], p[1]:pw - p[3]]
    return dispatch.call("fold", f, [x])


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    """Rearrange (C*r^2, H, W) -> (C, H*r, W*r) for sub-pixel conv (reference
    pixel_shuffle)."""
    x = _t(x)
    r = upscale_factor

    def f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        n, c, h, w = a.shape
        y = a.reshape(n, c // (r * r), r, r, h, w)
        y = jnp.transpose(y, (0, 1, 4, 2, 5, 3))
        y = y.reshape(n, c // (r * r), h * r, w * r)
        if data_format == "NHWC":
            y = jnp.transpose(y, (0, 2, 3, 1))
        return y
    return dispatch.call("pixel_shuffle", f, [x])


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    """Inverse of pixel_shuffle (reference pixel_unshuffle)."""
    x = _t(x)
    r = downscale_factor

    def f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        n, c, h, w = a.shape
        y = a.reshape(n, c, h // r, r, w // r, r)
        y = jnp.transpose(y, (0, 1, 3, 5, 2, 4))
        y = y.reshape(n, c * r * r, h // r, w // r)
        if data_format == "NHWC":
            y = jnp.transpose(y, (0, 2, 3, 1))
        return y
    return dispatch.call("pixel_unshuffle", f, [x])


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    """Interleave channel groups, ShuffleNet-style (reference channel_shuffle).
    """
    x = _t(x)

    def f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        n, c, h, w = a.shape
        y = a.reshape(n, groups, c // groups, h, w)
        y = jnp.swapaxes(y, 1, 2).reshape(n, c, h, w)
        if data_format == "NHWC":
            y = jnp.transpose(y, (0, 2, 3, 1))
        return y
    return dispatch.call("channel_shuffle", f, [x])


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    """Dot product of L2-normalized inputs along ``axis`` (reference
    cosine_similarity)."""
    def f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.maximum(
            jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis), eps)
        return num / den
    return dispatch.call("cosine_similarity", f, [_t(x1), _t(x2)])


def bilinear(x1, x2, weight, bias=None, name=None):
    """Bilinear form x1^T W x2 + b per output channel (reference bilinear)."""
    inputs = [_t(x1), _t(x2), _t(weight)]
    if bias is not None:
        inputs.append(_t(bias))

    def f(a, b, w, *bb):
        y = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bb:
            y = y + bb[0]
        return y
    return dispatch.call("bilinear", f, inputs)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    """Blend one-hot labels toward uniform (or prior_dist) by epsilon
    (reference label_smooth)."""
    label = _t(label)
    inputs = [label]
    if prior_dist is not None:
        inputs.append(_t(prior_dist))

    def f(lab, *pd):
        c = lab.shape[-1]
        if pd:
            return (1 - epsilon) * lab + epsilon * pd[0]
        return (1 - epsilon) * lab + epsilon / c
    return dispatch.call("label_smooth", f, inputs)


__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "embedding", "embedding_bag", "one_hot", "pad", "zeropad2d",
    "interpolate", "upsample",
    "unfold", "fold", "pixel_shuffle", "pixel_unshuffle", "channel_shuffle",
    "cosine_similarity", "bilinear", "label_smooth",
]


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    """[B] lengths -> [B, maxlen] 0/1 mask (reference ops.yaml
    sequence_mask). maxlen=None derives it from the (concrete) lengths
    BEFORE tracing — under capture, pass an explicit maxlen."""
    import jax as _jax

    from ...core import dispatch as _dispatch
    t = lengths if isinstance(lengths, Tensor) else as_tensor(lengths)
    if maxlen is None:
        if isinstance(t._data, _jax.core.Tracer):
            raise ValueError(
                "sequence_mask(maxlen=None) needs concrete lengths; pass "
                "an explicit maxlen under jit/to_static (shapes must be "
                "static)")
        # tpulint: disable=TPU103 — maxlen becomes an output SHAPE; guarded by the Tracer check above
        maxlen = int(jnp.max(t._data))

    def f(l):
        return (jnp.arange(maxlen)[None, :] < l[..., None]).astype(dtype)
    return _dispatch.call("sequence_mask", f, [t])

__all__ += ['sequence_mask']
