"""Pooling functionals.

Reference: python/paddle/nn/functional/pooling.py (phi pool kernels,
paddle/phi/kernels/funcs/pooling.h). TPU-native: ``lax.reduce_window`` — XLA
lowers it onto the VPU with fused padding; exclusive avg-pool divides by a
reduce_window over ones. Adaptive pools use the integral-image (cumsum +
gather) formulation so output shapes stay static for the compiler.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dispatch
from ...core.tensor import Tensor, as_tensor
from .conv import _ntuple, _resolve_padding


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _pool_nd(x, ksize, stride, padding, nd, channel_last, mode,
             exclusive=True, ceil_mode=False, op_name="pool"):
    ksize = _ntuple(ksize, nd)
    stride = _ntuple(stride if stride is not None else ksize, nd)
    pad = _resolve_padding(padding, nd, (1,) * nd, ksize)
    if isinstance(pad, str):
        pad_mode = pad
        pad = None
    else:
        pad_mode = None

    if channel_last:
        window = (1,) + ksize + (1,)
        strides = (1,) + stride + (1,)
        spatial_axes = tuple(range(1, 1 + nd))
    else:
        window = (1, 1) + ksize
        strides = (1, 1) + stride
        spatial_axes = tuple(range(2, 2 + nd))

    def full_padding(a):
        if pad_mode == "VALID":
            return [(0, 0)] * a.ndim
        if pad_mode == "SAME":
            cfg = []
            j = 0
            for i in range(a.ndim):
                if i in spatial_axes:
                    out = -(-a.shape[i] // stride[j])
                    total = max((out - 1) * stride[j] + ksize[j] - a.shape[i], 0)
                    cfg.append((total // 2, total - total // 2))
                    j += 1
                else:
                    cfg.append((0, 0))
            return cfg
        cfg = [(0, 0)] * a.ndim
        for j, ax in enumerate(spatial_axes):
            lo, hi = pad[j]
            if ceil_mode:
                size = a.shape[ax] + lo + hi
                rem = (size - ksize[j]) % stride[j]
                if rem:
                    hi += stride[j] - rem
            cfg[ax] = (lo, hi)
        return cfg

    def f(a):
        cfg = full_padding(a)
        if mode == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, init, jax.lax.max, window, strides, cfg)
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, cfg)
        if exclusive:
            ones = jnp.ones(a.shape, dtype=a.dtype)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, cfg)
            return s / cnt
        return s / float(np.prod(ksize))
    return dispatch.call(op_name, f, [x], export_attrs={
        "kernel_size": ksize, "stride": stride,
        "padding": pad if pad is not None else pad_mode, "mode": mode,
        "exclusive": exclusive, "ceil_mode": ceil_mode,
        "channel_last": channel_last})


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    """1D average pooling, NCL (reference avg_pool1d)."""
    return _pool_nd(_t(x), kernel_size, stride, padding, 1, False, "avg",
                    exclusive, ceil_mode, "avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    """2D average pooling, NCHW (reference avg_pool2d)."""
    if divisor_override is not None:
        t = _pool_nd(_t(x), kernel_size, stride, padding, 2,
                     data_format == "NHWC", "avg", False, ceil_mode,
                     "avg_pool2d")
        k = float(np.prod(_ntuple(kernel_size, 2)))
        return dispatch.call("scale", lambda a: a * (k / divisor_override), [t])
    return _pool_nd(_t(x), kernel_size, stride, padding, 2,
                    data_format == "NHWC", "avg", exclusive, ceil_mode,
                    "avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    """3D average pooling, NCDHW (reference avg_pool3d)."""
    return _pool_nd(_t(x), kernel_size, stride, padding, 3,
                    data_format == "NDHWC", "avg", exclusive, ceil_mode,
                    "avg_pool3d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    """1D max pooling, NCL; optional argmax indices (reference max_pool1d)."""
    out = _pool_nd(_t(x), kernel_size, stride, padding, 1, False, "max",
                   ceil_mode=ceil_mode, op_name="max_pool1d")
    if return_mask:
        return out, _max_pool_indices(_t(x), kernel_size, stride, padding, 1, False, ceil_mode)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    """2D max pooling, NCHW; optional argmax indices (reference max_pool2d)."""
    out = _pool_nd(_t(x), kernel_size, stride, padding, 2,
                   data_format == "NHWC", "max", ceil_mode=ceil_mode,
                   op_name="max_pool2d")
    if return_mask:
        return out, _max_pool_indices(_t(x), kernel_size, stride, padding, 2,
                                      data_format == "NHWC", ceil_mode)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    """3D max pooling, NCDHW (reference max_pool3d)."""
    out = _pool_nd(_t(x), kernel_size, stride, padding, 3,
                   data_format == "NDHWC", "max", ceil_mode=ceil_mode,
                   op_name="max_pool3d")
    if return_mask:
        return out, _max_pool_indices(_t(x), kernel_size, stride, padding, 3,
                                      data_format == "NDHWC", ceil_mode)
    return out


def _max_pool_indices(x, ksize, stride, padding, nd, channel_last,
                      ceil_mode=False):
    """Flat spatial argmax per window (reference max_pool return_mask)."""
    ksize_t = _ntuple(ksize, nd)
    stride_t = _ntuple(stride if stride is not None else ksize, nd)
    pad = _resolve_padding(padding, nd, (1,) * nd, ksize_t)
    if isinstance(pad, str):
        pad = [(0, 0)] * nd

    def f(a):
        if channel_last:
            perm = (0, a.ndim - 1) + tuple(range(1, a.ndim - 1))
            a = jnp.transpose(a, perm)
        spatial = a.shape[2:]
        flat_idx = jnp.arange(int(np.prod(spatial))).reshape(spatial)
        flat_idx = jnp.broadcast_to(flat_idx, a.shape).astype(jnp.int32)
        window = (1, 1) + ksize_t
        strides = (1, 1) + stride_t
        eff_pad = []
        for j in range(nd):
            lo, hi = pad[j]
            if ceil_mode:
                size = spatial[j] + lo + hi
                rem = (size - ksize_t[j]) % stride_t[j]
                if rem:
                    hi += stride_t[j] - rem
            eff_pad.append((lo, hi))
        cfg = [(0, 0), (0, 0)] + eff_pad

        def reducer(acc, cur):
            av, ai = acc
            cv, ci = cur
            take = cv > av
            return jnp.where(take, cv, av), jnp.where(take, ci, ai)
        init_v = jnp.asarray(-jnp.inf, a.dtype)
        init_i = jnp.asarray(-1, jnp.int32)
        _, idx = jax.lax.reduce_window((a, flat_idx), (init_v, init_i), reducer,
                                       window, strides, cfg)
        return idx
    return dispatch.call("max_pool_mask", f, [x],
                         differentiable_mask=[False])


def _adaptive_pool_nd(x, output_size, nd, channel_last, mode, op_name):
    out = _ntuple(output_size, nd) if output_size is not None else None

    def f(a):
        if channel_last:
            perm = (0, a.ndim - 1) + tuple(range(1, a.ndim - 1))
            a = jnp.transpose(a, perm)
        spatial = a.shape[2:]
        osize = tuple(o if o is not None else spatial[i]
                      for i, o in enumerate(out))
        y = a
        if all(spatial[i] % osize[i] == 0 for i in range(nd)):
            # Fast path: reshape + reduce (static, MXU/VPU friendly).
            shape = list(a.shape[:2])
            red_axes = []
            for i in range(nd):
                k = spatial[i] // osize[i]
                shape += [osize[i], k]
                red_axes.append(len(shape) - 1)
            y = a.reshape(shape)
            y = (jnp.max(y, axis=tuple(red_axes)) if mode == "max"
                 else jnp.mean(y, axis=tuple(red_axes)))
        else:
            # General path: per-axis gather of uneven windows.
            for i in range(nd):
                ax = 2 + i
                in_sz, out_sz = spatial[i], osize[i]
                starts = (np.arange(out_sz) * in_sz) // out_sz
                ends = -(-((np.arange(out_sz) + 1) * in_sz) // out_sz)
                max_k = int((ends - starts).max())
                gather_idx = np.minimum(
                    starts[:, None] + np.arange(max_k)[None, :], in_sz - 1)
                valid = (starts[:, None] + np.arange(max_k)[None, :]) < ends[:, None]
                g = jnp.take(y, jnp.asarray(gather_idx.reshape(-1)), axis=ax)
                new_shape = g.shape[:ax] + (out_sz, max_k) + g.shape[ax + 1:]
                g = g.reshape(new_shape)
                vshape = [1] * g.ndim
                vshape[ax], vshape[ax + 1] = out_sz, max_k
                v = jnp.asarray(valid).reshape(vshape)
                if mode == "max":
                    g = jnp.where(v, g, -jnp.inf)
                    y = jnp.max(g, axis=ax + 1)
                else:
                    g = jnp.where(v, g, 0.0)
                    y = jnp.sum(g, axis=ax + 1) / jnp.sum(v, axis=ax + 1)
        if channel_last:
            inv = (0,) + tuple(range(2, 2 + nd)) + (1,)
            y = jnp.transpose(y, inv)
        return y
    return dispatch.call(op_name, f, [x], export_attrs={
        "output_size": output_size, "mode": mode,
        "channel_last": channel_last})


def adaptive_avg_pool1d(x, output_size, name=None):
    """Average pool to a target output length (reference adaptive_avg_pool1d).
    """
    return _adaptive_pool_nd(_t(x), output_size, 1, False, "avg",
                             "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    """Average pool to a target (H, W) (reference adaptive_avg_pool2d)."""
    return _adaptive_pool_nd(_t(x), output_size, 2, data_format == "NHWC",
                             "avg", "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    """Average pool to a target (D, H, W) (reference adaptive_avg_pool3d)."""
    return _adaptive_pool_nd(_t(x), output_size, 3, data_format == "NDHWC",
                             "avg", "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    """Max pool to a target output length (reference adaptive_max_pool1d)."""
    out = _adaptive_pool_nd(_t(x), output_size, 1, False, "max",
                            "adaptive_max_pool1d")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    """Max pool to a target (H, W) (reference adaptive_max_pool2d)."""
    out = _adaptive_pool_nd(_t(x), output_size, 2, False, "max",
                            "adaptive_max_pool2d")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    """Max pool to a target (D, H, W) (reference adaptive_max_pool3d)."""
    out = _adaptive_pool_nd(_t(x), output_size, 3, False, "max",
                            "adaptive_max_pool3d")
    return (out, None) if return_mask else out


def _max_unpool_nd(x, indices, kernel_size, stride, padding, nd,
                   output_size, op_name):
    """Scatter pooled values back to their argmax positions (reference
    unpool/unpool3d kernels, phi/kernels/gpu/unpool_kernel.cu). The flat
    spatial ``indices`` come from max_poolNd(return_mask=True)."""
    x, indices = _t(x), _t(indices)
    ksize = _ntuple(kernel_size, nd)
    stride_t = _ntuple(stride if stride is not None else kernel_size, nd)
    pad = _ntuple(padding, nd)
    in_spatial = x.shape[2:]
    if output_size is None:
        out_spatial = tuple(
            (in_spatial[i] - 1) * stride_t[i] - 2 * pad[i] + ksize[i]
            for i in range(nd))
    else:
        out_spatial = tuple(output_size[-nd:])

    def f(a, idx):
        n, c = a.shape[:2]
        flat = int(np.prod(out_spatial))
        k = int(np.prod(a.shape[2:]))
        av = a.reshape(n * c, k)
        iv = idx.reshape(n * c, k).astype(jnp.int32)
        out = jnp.zeros((n * c, flat), dtype=a.dtype)
        rows = jnp.arange(n * c)[:, None]
        out = out.at[rows, iv].set(av)
        return out.reshape((n, c) + out_spatial)

    return dispatch.call(op_name, f, [x, indices],
                         differentiable_mask=[True, False])


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """Scatter pooled values back to argmax positions, 1D (reference
    max_unpool1d)."""
    return _max_unpool_nd(x, indices, kernel_size, stride, padding, 1,
                          output_size, "max_unpool1d")


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """Scatter pooled values back to argmax positions, 2D (reference
    max_unpool2d)."""
    return _max_unpool_nd(x, indices, kernel_size, stride, padding, 2,
                          output_size, "max_unpool2d")


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """Scatter pooled values back to argmax positions, 3D (reference
    max_unpool3d)."""
    return _max_unpool_nd(x, indices, kernel_size, stride, padding, 3,
                          output_size, "max_unpool3d")


def _fractional_intervals(u, in_size, out_size, pool_size):
    """Pseudo-random pooling-region starts (Graham, Fractional Max-Pooling;
    same sequence rule as the reference fractional_max_pool kernels)."""
    starts = np.zeros(out_size, dtype=np.int64)
    if out_size > 1:
        alpha = (in_size - pool_size) / (out_size - 1)
        i = np.arange(out_size - 1)
        starts[:-1] = ((i + u) * alpha).astype(np.int64) - int(u * alpha)
    starts[out_size - 1] = in_size - pool_size
    return starts


def _fractional_max_pool_nd(x, output_size, kernel_size, random_u, nd,
                            return_mask, op_name):
    x = _t(x)
    out_sz = _ntuple(output_size, nd)
    in_spatial = x.shape[2:]
    if kernel_size is None:
        ksize = tuple(in_spatial[i] // out_sz[i] for i in range(nd))
    else:
        ksize = _ntuple(kernel_size, nd)
    if random_u is None:
        from ...core.generator import default_generator
        import jax as _jax
        # tpulint: disable=TPU103 — u picks the pooling GRID (static shapes); must be a host scalar
        u = float(_jax.random.uniform(default_generator().next_key(), ()))
    else:
        u = float(random_u)
    starts = [_fractional_intervals(u, in_spatial[i], out_sz[i], ksize[i])
              for i in range(nd)]

    def f(a):
        # one gather + running max per static kernel offset (k^nd of them,
        # fused by XLA); flat argmax tracked alongside for return_mask
        idx_axes = [jnp.asarray(starts[i]) for i in range(nd)]
        out = None
        mask = None
        for off in np.ndindex(*ksize):
            coords = [idx_axes[i] + off[i] for i in range(nd)]
            v = a
            flat = 0
            for i, cc in enumerate(coords):
                v = jnp.take(v, cc, axis=2 + i)
                flat = flat * in_spatial[i] + cc.reshape(
                    (-1,) + (1,) * (nd - 1 - i))
            if out is None:
                out, mask = v, jnp.broadcast_to(flat, v.shape)
            else:
                upd = v > out
                mask = jnp.where(upd, jnp.broadcast_to(flat, v.shape), mask)
                out = jnp.maximum(out, v)
        return out, mask.astype(jnp.int32)

    out, mask = dispatch.call(op_name, f, [x])
    return (out, mask) if return_mask else out


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """Fractional max pooling (reference fractional_max_pool2d,
    phi/kernels/impl/fractional_max_pool_kernel_impl.h)."""
    return _fractional_max_pool_nd(x, output_size, kernel_size, random_u, 2,
                                   return_mask, "fractional_max_pool2d")


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """Max pool over pseudo-random fractional intervals, 3D (reference
    fractional_max_pool3d)."""
    return _fractional_max_pool_nd(x, output_size, kernel_size, random_u, 3,
                                   return_mask, "fractional_max_pool3d")


def _lp_pool(x, norm_type, kernel_size, stride, padding, nd, ceil_mode,
             data_format, op_name):
    """Power-average pooling: (sum |x|^p)^(1/p) (reference lp_pool2d,
    phi lp pool kernels; p=inf degenerates to max pool)."""
    x = _t(x)
    p = float(norm_type)
    if p == float("inf"):
        return _pool_nd(x, kernel_size, stride, padding, nd,
                        data_format in ("NHWC", "NLC"), "max",
                        ceil_mode=ceil_mode, op_name=op_name)

    def f(a):
        return jnp.abs(a) ** p

    powed = dispatch.call(op_name + "_pow", f, [x])
    s = _pool_nd(powed, kernel_size, stride, padding, nd,
                 data_format in ("NHWC", "NLC"), "avg", exclusive=False,
                 ceil_mode=ceil_mode, op_name=op_name)
    k = float(np.prod(_ntuple(kernel_size, nd)))
    return dispatch.call(op_name + "_root",
                         lambda a: (a * k) ** (1.0 / p), [s])


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """Lp-norm pooling, 1D (reference lp_pool1d)."""
    return _lp_pool(x, norm_type, kernel_size, stride, padding, 1, ceil_mode,
                    data_format, "lp_pool1d")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    """Lp-norm pooling, 2D (reference lp_pool2d)."""
    return _lp_pool(x, norm_type, kernel_size, stride, padding, 2, ceil_mode,
                    data_format, "lp_pool2d")


__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d", "max_unpool1d", "max_unpool2d", "max_unpool3d",
    "fractional_max_pool2d", "fractional_max_pool3d", "lp_pool1d",
    "lp_pool2d",
]
