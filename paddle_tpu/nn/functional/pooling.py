"""Pooling functionals.

Reference: python/paddle/nn/functional/pooling.py (phi pool kernels,
paddle/phi/kernels/funcs/pooling.h). TPU-native: ``lax.reduce_window`` — XLA
lowers it onto the VPU with fused padding; exclusive avg-pool divides by a
reduce_window over ones. Adaptive pools use the integral-image (cumsum +
gather) formulation so output shapes stay static for the compiler.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dispatch
from ...core.tensor import Tensor, as_tensor
from .conv import _ntuple, _resolve_padding


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _pool_nd(x, ksize, stride, padding, nd, channel_last, mode,
             exclusive=True, ceil_mode=False, op_name="pool"):
    ksize = _ntuple(ksize, nd)
    stride = _ntuple(stride if stride is not None else ksize, nd)
    pad = _resolve_padding(padding, nd, (1,) * nd, ksize)
    if isinstance(pad, str):
        pad_mode = pad
        pad = None
    else:
        pad_mode = None

    if channel_last:
        window = (1,) + ksize + (1,)
        strides = (1,) + stride + (1,)
        spatial_axes = tuple(range(1, 1 + nd))
    else:
        window = (1, 1) + ksize
        strides = (1, 1) + stride
        spatial_axes = tuple(range(2, 2 + nd))

    def full_padding(a):
        if pad_mode == "VALID":
            return [(0, 0)] * a.ndim
        if pad_mode == "SAME":
            cfg = []
            j = 0
            for i in range(a.ndim):
                if i in spatial_axes:
                    out = -(-a.shape[i] // stride[j])
                    total = max((out - 1) * stride[j] + ksize[j] - a.shape[i], 0)
                    cfg.append((total // 2, total - total // 2))
                    j += 1
                else:
                    cfg.append((0, 0))
            return cfg
        cfg = [(0, 0)] * a.ndim
        for j, ax in enumerate(spatial_axes):
            lo, hi = pad[j]
            if ceil_mode:
                size = a.shape[ax] + lo + hi
                rem = (size - ksize[j]) % stride[j]
                if rem:
                    hi += stride[j] - rem
            cfg[ax] = (lo, hi)
        return cfg

    def f(a):
        cfg = full_padding(a)
        if mode == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, init, jax.lax.max, window, strides, cfg)
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides, cfg)
        if exclusive:
            ones = jnp.ones(a.shape, dtype=a.dtype)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, cfg)
            return s / cnt
        return s / float(np.prod(ksize))
    return dispatch.call(op_name, f, [x])


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool_nd(_t(x), kernel_size, stride, padding, 1, False, "avg",
                    exclusive, ceil_mode, "avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    if divisor_override is not None:
        t = _pool_nd(_t(x), kernel_size, stride, padding, 2,
                     data_format == "NHWC", "avg", False, ceil_mode,
                     "avg_pool2d")
        k = float(np.prod(_ntuple(kernel_size, 2)))
        return dispatch.call("scale", lambda a: a * (k / divisor_override), [t])
    return _pool_nd(_t(x), kernel_size, stride, padding, 2,
                    data_format == "NHWC", "avg", exclusive, ceil_mode,
                    "avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool_nd(_t(x), kernel_size, stride, padding, 3,
                    data_format == "NDHWC", "avg", exclusive, ceil_mode,
                    "avg_pool3d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    out = _pool_nd(_t(x), kernel_size, stride, padding, 1, False, "max",
                   ceil_mode=ceil_mode, op_name="max_pool1d")
    if return_mask:
        return out, _max_pool_indices(_t(x), kernel_size, stride, padding, 1, False, ceil_mode)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool_nd(_t(x), kernel_size, stride, padding, 2,
                   data_format == "NHWC", "max", ceil_mode=ceil_mode,
                   op_name="max_pool2d")
    if return_mask:
        return out, _max_pool_indices(_t(x), kernel_size, stride, padding, 2,
                                      data_format == "NHWC", ceil_mode)
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool_nd(_t(x), kernel_size, stride, padding, 3,
                   data_format == "NDHWC", "max", ceil_mode=ceil_mode,
                   op_name="max_pool3d")
    if return_mask:
        return out, _max_pool_indices(_t(x), kernel_size, stride, padding, 3,
                                      data_format == "NDHWC", ceil_mode)
    return out


def _max_pool_indices(x, ksize, stride, padding, nd, channel_last,
                      ceil_mode=False):
    """Flat spatial argmax per window (reference max_pool return_mask)."""
    ksize_t = _ntuple(ksize, nd)
    stride_t = _ntuple(stride if stride is not None else ksize, nd)
    pad = _resolve_padding(padding, nd, (1,) * nd, ksize_t)
    if isinstance(pad, str):
        pad = [(0, 0)] * nd

    def f(a):
        if channel_last:
            perm = (0, a.ndim - 1) + tuple(range(1, a.ndim - 1))
            a = jnp.transpose(a, perm)
        spatial = a.shape[2:]
        flat_idx = jnp.arange(int(np.prod(spatial))).reshape(spatial)
        flat_idx = jnp.broadcast_to(flat_idx, a.shape).astype(jnp.int32)
        window = (1, 1) + ksize_t
        strides = (1, 1) + stride_t
        eff_pad = []
        for j in range(nd):
            lo, hi = pad[j]
            if ceil_mode:
                size = spatial[j] + lo + hi
                rem = (size - ksize_t[j]) % stride_t[j]
                if rem:
                    hi += stride_t[j] - rem
            eff_pad.append((lo, hi))
        cfg = [(0, 0), (0, 0)] + eff_pad

        def reducer(acc, cur):
            av, ai = acc
            cv, ci = cur
            take = cv > av
            return jnp.where(take, cv, av), jnp.where(take, ci, ai)
        init_v = jnp.asarray(-jnp.inf, a.dtype)
        init_i = jnp.asarray(-1, jnp.int32)
        _, idx = jax.lax.reduce_window((a, flat_idx), (init_v, init_i), reducer,
                                       window, strides, cfg)
        return idx
    return dispatch.call("max_pool_mask", f, [x],
                         differentiable_mask=[False])


def _adaptive_pool_nd(x, output_size, nd, channel_last, mode, op_name):
    out = _ntuple(output_size, nd) if output_size is not None else None

    def f(a):
        if channel_last:
            perm = (0, a.ndim - 1) + tuple(range(1, a.ndim - 1))
            a = jnp.transpose(a, perm)
        spatial = a.shape[2:]
        osize = tuple(o if o is not None else spatial[i]
                      for i, o in enumerate(out))
        y = a
        if all(spatial[i] % osize[i] == 0 for i in range(nd)):
            # Fast path: reshape + reduce (static, MXU/VPU friendly).
            shape = list(a.shape[:2])
            red_axes = []
            for i in range(nd):
                k = spatial[i] // osize[i]
                shape += [osize[i], k]
                red_axes.append(len(shape) - 1)
            y = a.reshape(shape)
            y = (jnp.max(y, axis=tuple(red_axes)) if mode == "max"
                 else jnp.mean(y, axis=tuple(red_axes)))
        else:
            # General path: per-axis gather of uneven windows.
            for i in range(nd):
                ax = 2 + i
                in_sz, out_sz = spatial[i], osize[i]
                starts = (np.arange(out_sz) * in_sz) // out_sz
                ends = -(-((np.arange(out_sz) + 1) * in_sz) // out_sz)
                max_k = int((ends - starts).max())
                gather_idx = np.minimum(
                    starts[:, None] + np.arange(max_k)[None, :], in_sz - 1)
                valid = (starts[:, None] + np.arange(max_k)[None, :]) < ends[:, None]
                g = jnp.take(y, jnp.asarray(gather_idx.reshape(-1)), axis=ax)
                new_shape = g.shape[:ax] + (out_sz, max_k) + g.shape[ax + 1:]
                g = g.reshape(new_shape)
                vshape = [1] * g.ndim
                vshape[ax], vshape[ax + 1] = out_sz, max_k
                v = jnp.asarray(valid).reshape(vshape)
                if mode == "max":
                    g = jnp.where(v, g, -jnp.inf)
                    y = jnp.max(g, axis=ax + 1)
                else:
                    g = jnp.where(v, g, 0.0)
                    y = jnp.sum(g, axis=ax + 1) / jnp.sum(v, axis=ax + 1)
        if channel_last:
            inv = (0,) + tuple(range(2, 2 + nd)) + (1,)
            y = jnp.transpose(y, inv)
        return y
    return dispatch.call(op_name, f, [x])


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool_nd(_t(x), output_size, 1, False, "avg",
                             "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool_nd(_t(x), output_size, 2, data_format == "NHWC",
                             "avg", "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool_nd(_t(x), output_size, 3, data_format == "NDHWC",
                             "avg", "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool_nd(_t(x), output_size, 1, False, "max",
                            "adaptive_max_pool1d")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool_nd(_t(x), output_size, 2, False, "max",
                            "adaptive_max_pool2d")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool_nd(_t(x), output_size, 3, False, "max",
                            "adaptive_max_pool3d")
    return (out, None) if return_mask else out


__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d",
]
