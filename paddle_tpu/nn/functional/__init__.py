"""paddle_tpu.nn.functional — functional neural net ops.

Reference surface: python/paddle/nn/functional/__init__.py.
"""
from .activation import *  # noqa: F401,F403
from .common import *      # noqa: F401,F403
from .conv import *        # noqa: F401,F403
from .pooling import *     # noqa: F401,F403
from .norm import *        # noqa: F401,F403
from .loss import *        # noqa: F401,F403
from .flash_attention import *  # noqa: F401,F403
from .vision import *      # noqa: F401,F403
from .paged_attention import *  # noqa: F401,F403
from .fused import *       # noqa: F401,F403
from .tail import *        # noqa: F401,F403
from ...ops.search import class_center_sample, gather_tree  # noqa: F401

from . import (activation, common, conv, flash_attention, fused, loss,
               norm, paged_attention, pooling, tail, vision)
