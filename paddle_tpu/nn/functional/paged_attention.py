"""Paged (block-table) KV-cache attention for serving.

Reference: block_multi_head_attention
(phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu, exposed via
python/paddle/incubate/nn/functional/block_multihead_attention.py) — the
vLLM-style paged KV cache: the KV history of each sequence lives in
fixed-size physical blocks referenced through a per-sequence block table,
so sequences grow without reallocating or compacting.

TPU-native design: the cache is one (num_blocks, block_size, KVH, D) array
per K/V; a step is (1) scatter the step's new KV into physical slots
computed from the block table (one `.at[].set` with batched indices), then
(2) per sequence gather its blocks back into a contiguous (S_max, KVH, D)
view and run masked attention — gathers + one MXU einsum, all static
shapes, fully jittable into a serving step. GQA/MQA supported (H a
multiple of KVH).

int8 page pool (the serving tier's ``kv_dtype="int8"`` knob): pass int8
caches plus sidecar per-(position, head) scale arrays ``k_scale`` /
``v_scale`` of shape (num_blocks, block_size, KVH). New KV is quantized
symmetric-abs-max over the head dim on write (``ops.pallas.serving``),
and the gather dequantizes into the attention math's fp32 accumulation —
the same payload-int8 / sidecar-scales / dequant-at-consumer pattern as
``nn.quant.weight_only_linear``, applied to KV pages. Resident KV shrinks
~2x vs bf16 pages, which is resident-batch headroom on a serving chip.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core.tensor import Tensor, as_tensor

__all__ = ["block_multihead_attention"]


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def block_multihead_attention(q, key_cache, value_cache, block_tables,
                              seq_lens, new_k=None, new_v=None, causal=True,
                              scale=None, k_scale=None, v_scale=None,
                              name=None):
    """Attend over paged KV history (+ optionally append this step's KV).

    Args:
      q: (B, T, H, D) queries for the T newest positions of each sequence
         (T=1 decode; T>1 chunked prefill / speculative verify).
      key_cache / value_cache: (num_blocks, block_size, KVH, D). Float
         pages, or int8 pages when ``k_scale``/``v_scale`` are given.
      block_tables: (B, max_blocks_per_seq) int32 physical block ids;
         entries beyond a sequence's allocation may be any valid id (they
         are masked by seq_lens).
      seq_lens: (B,) int32 sequence lengths INCLUDING the new T tokens.
      new_k / new_v: (B, T, KVH, D) — written into the caches at positions
         [len-T, len) before attending. Omit for read-only attention.
      causal: within the T new positions, query t sees history up to and
         including its own slot.
      k_scale / v_scale: (num_blocks, block_size, KVH) float32 sidecar
         scales for int8 caches. New KV is quantized on write; the
         per-sequence gather dequantizes.

    Returns (out (B, T, H, D), key_cache, value_cache) — plus the updated
    (k_scale, v_scale) appended when int8 caches are used. Caches update
    functionally (donate them in a jitted serving step for in-place
    reuse).
    """
    q, kc, vc = _t(q), _t(key_cache), _t(value_cache)
    bt, sl = _t(block_tables), _t(seq_lens)
    tensors = [q, kc, vc, bt, sl]
    has_new = new_k is not None
    if has_new:
        new_k, new_v = _t(new_k), _t(new_v)
        tensors += [new_k, new_v]
    quantized = k_scale is not None
    if quantized:
        if v_scale is None:
            raise ValueError("int8 KV cache needs both k_scale and v_scale")
        ks_t, vs_t = _t(k_scale), _t(v_scale)
        tensors += [ks_t, vs_t]

    def f(qa, kca, vca, bta, sla, *rest):
        from ...ops.pallas.serving import (kv_dequantize_int8,
                                           kv_quantize_int8)

        B, T, H, D = qa.shape
        nb, bs, KVH, _ = kca.shape
        max_blocks = bta.shape[1]
        s_max = max_blocks * bs
        if H % KVH:
            raise ValueError(f"H={H} not a multiple of KVH={KVH}")
        group = H // KVH
        sla_i = sla.astype(jnp.int32)
        bta_i = bta.astype(jnp.int32)
        ksa = vsa = None
        if quantized:
            ksa, vsa = rest[-2:]
            rest = rest[:-2]

        if has_new:
            nk, nv = rest
            # flat slot of new token t of seq b: pos = len - T + t. Rows
            # with seq_len < T (padded batch rows) would yield negative
            # positions that WRAP into live blocks — drop those writes.
            pos = sla_i[:, None] - T + jnp.arange(T)[None, :]     # (B, T)
            ok = pos >= 0
            blk = jnp.take_along_axis(bta_i, jnp.maximum(pos, 0) // bs,
                                      axis=1)                     # (B, T)
            blk = jnp.where(ok, blk, nb)  # out-of-range -> mode="drop"
            off = jnp.maximum(pos, 0) % bs
            if quantized:
                qk, sk = kv_quantize_int8(nk)
                qv, sv = kv_quantize_int8(nv)
                kca = kca.at[blk, off].set(qk, mode="drop")
                vca = vca.at[blk, off].set(qv, mode="drop")
                ksa = ksa.at[blk, off].set(sk, mode="drop")
                vsa = vsa.at[blk, off].set(sv, mode="drop")
            else:
                kca = kca.at[blk, off].set(nk, mode="drop")
                vca = vca.at[blk, off].set(nv, mode="drop")

        sc = scale if scale is not None else 1.0 / (D ** 0.5)

        def per_seq(blocks, length, qb):
            # gather this sequence's pages -> (s_max, KVH, D)
            if quantized:
                k = kv_dequantize_int8(kca[blocks], ksa[blocks])
                v = kv_dequantize_int8(vca[blocks], vsa[blocks])
                k = k.reshape(s_max, KVH, D)
                v = v.reshape(s_max, KVH, D)
            else:
                k = kca[blocks].reshape(s_max, KVH, D)
                v = vca[blocks].reshape(s_max, KVH, D)
            qg = qb.reshape(T, KVH, group, D)
            s = jnp.einsum("tkgd,skd->tkgs", qg.astype(jnp.float32),
                           k.astype(jnp.float32)) * sc
            jpos = jnp.arange(s_max)[None, None, None, :]
            qpos = (length - T + jnp.arange(T)).reshape(T, 1, 1, 1)
            mask = jpos < length
            if causal:
                mask = jpos <= qpos
            # -1e30 (not -inf) + explicit zeroing of fully-masked rows:
            # a padded row (length <= 0) must yield 0, not NaN
            s = jnp.where(mask, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("tkgs,skd->tkgd", p, v.astype(jnp.float32))
            any_valid = mask.any(axis=-1, keepdims=True)
            o = jnp.where(any_valid, o, 0.0)
            return o.reshape(T, H, D).astype(qb.dtype)

        out = jax.vmap(per_seq)(bta_i, sla_i, qa)
        if quantized:
            return out, kca, vca, ksa, vsa
        return out, kca, vca

    # int8 caches/scales are not differentiable surfaces (round/clip);
    # the float path keeps its original cache lineage for trainers that
    # backprop through read-only paged attention.
    mask = ([True] + [not quantized] * 2 + [False, False]
            + [True, True] * has_new + [False, False] * quantized)
    return dispatch.call("block_multihead_attention", f, tensors,
                         differentiable_mask=mask)
