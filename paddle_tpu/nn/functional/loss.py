"""Loss functionals.

Reference: python/paddle/nn/functional/loss.py (cross_entropy at its heart is
phi softmax_with_cross_entropy). Labels are non-differentiable inputs; the
dispatcher routes float0 cotangents around them automatically.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dispatch
from ...core.tensor import Tensor, as_tensor


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _reduce(val, reduction, weight_sum=None):
    if reduction == "none":
        return val
    if reduction == "sum":
        return jnp.sum(val)
    if weight_sum is not None:
        return jnp.sum(val) / weight_sum
    return jnp.mean(val)


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """softmax+CE in one fused lowering (reference: loss.py cross_entropy →
    _C_ops.cross_entropy_with_softmax)."""
    input, label = _t(input), _t(label)
    inputs = [input, label]
    has_w = weight is not None
    if has_w:
        inputs.append(_t(weight))

    def f(logits, lab, *w):
        ax = axis if axis >= 0 else logits.ndim + axis
        c = logits.shape[ax]
        hard = not (soft_label or (lab.ndim == logits.ndim
                                   and lab.shape[ax] == c
                                   and jnp.issubdtype(lab.dtype,
                                                      jnp.floating)))
        if use_softmax and hard:
            # streaming formulation: nll = lse - logits[label]. Never
            # materializes an f32 (N, V) log-prob tensor — the f32 cast
            # fuses into the reductions, the big buffer stays in the
            # input dtype (bf16 under AMP). Cuts the GPT-class lm-head
            # loss from ~5 HBM passes of f32 to ~3 passes of bf16.
            m = jax.lax.stop_gradient(
                jnp.max(logits, axis=ax, keepdims=True))
            shifted = (logits - m).astype(jnp.float32)
            sumexp = jnp.sum(jnp.exp(shifted), axis=ax)
            lse = jnp.log(sumexp) + jnp.squeeze(m.astype(jnp.float32), ax)
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == logits.ndim and lab_i.shape[ax] == 1:
                lab_i = jnp.squeeze(lab_i, axis=ax)
            valid = lab_i != ignore_index
            safe = jnp.where(valid, lab_i, 0)
            picked = jnp.squeeze(jnp.take_along_axis(
                logits, jnp.expand_dims(safe, ax), axis=ax), ax)
            nll = lse - picked.astype(jnp.float32)
            if label_smoothing > 0:
                # mean_logp = mean(logits) - lse
                smooth = lse - jnp.mean(logits.astype(jnp.float32), axis=ax)
                nll = (1 - label_smoothing) * nll + label_smoothing * smooth
            return _hard_label_reduce(nll, valid, w, has_w, safe, reduction)
        if use_softmax:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=ax)
        else:
            logp = jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-15, 1.0))
        if not hard:
            soft = lab.astype(jnp.float32)
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / c
            loss = -jnp.sum(soft * logp, axis=ax)
            if has_w:
                wvec = w[0].astype(jnp.float32)
                loss = loss * jnp.sum(soft * wvec.reshape(
                    [1] * ax + [c] + [1] * (logits.ndim - ax - 1)), axis=ax)
            return _reduce(loss, reduction)
        lab_i = lab.astype(jnp.int32)
        if lab_i.ndim == logits.ndim and lab_i.shape[ax] == 1:
            lab_i = jnp.squeeze(lab_i, axis=ax)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, ax),
                                     axis=ax)
        nll = -jnp.squeeze(picked, axis=ax)
        if label_smoothing > 0:
            smooth = -jnp.mean(logp, axis=ax)
            nll = (1 - label_smoothing) * nll + label_smoothing * smooth
        return _hard_label_reduce(nll, valid, w, has_w, safe, reduction)
    return dispatch.call("cross_entropy", f, inputs,
                         differentiable_mask=[True, soft_label] + [False] * has_w)


def _hard_label_reduce(nll, valid, w, has_w, safe, reduction):
    """Shared ignore_index/weight epilogue of both hard-label CE paths."""
    if has_w:
        wv = jnp.take(w[0].astype(jnp.float32), safe)
        nll = nll * wv
        nll = jnp.where(valid, nll, 0.0)
        if reduction == "mean":
            return jnp.sum(nll) / jnp.maximum(
                jnp.sum(jnp.where(valid, wv, 0.0)), 1e-12)
        return _reduce(nll, reduction)
    nll = jnp.where(valid, nll, 0.0)
    if reduction == "mean":
        return jnp.sum(nll) / jnp.maximum(jnp.sum(valid), 1)
    return _reduce(nll, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    """Fused softmax + cross entropy on logits (reference
    softmax_with_cross_entropy)."""
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    from .activation import softmax as _softmax
    loss = dispatch.call("unsqueeze", lambda a: jnp.expand_dims(a, axis), [loss])
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    """Negative log likelihood over log-probabilities with hard labels
    (reference nll_loss)."""
    input, label = _t(input), _t(label)
    inputs = [input, label]
    has_w = weight is not None
    if has_w:
        inputs.append(_t(weight))

    def f(logp, lab, *w):
        lab_i = lab.astype(jnp.int32)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
        nll = -jnp.squeeze(picked, axis=1)
        wv = (jnp.take(w[0], safe) if has_w
              else jnp.ones_like(nll))
        nll = jnp.where(valid, nll * wv, 0.0)
        if reduction == "mean":
            return jnp.sum(nll) / jnp.maximum(
                jnp.sum(jnp.where(valid, wv, 0.0)), 1e-12)
        return _reduce(nll, reduction)
    return dispatch.call("nll_loss", f, inputs,
                         differentiable_mask=[True, False] + [False] * has_w)


def mse_loss(input, label, reduction="mean", name=None):
    """Mean squared error (reference mse_loss)."""
    return dispatch.call(
        "mse_loss",
        lambda a, b: _reduce((a - b.astype(a.dtype)) ** 2, reduction),
        [_t(input), _t(label)])


def l1_loss(input, label, reduction="mean", name=None):
    """Mean absolute error (reference l1_loss)."""
    return dispatch.call(
        "l1_loss",
        lambda a, b: _reduce(jnp.abs(a - b.astype(a.dtype)), reduction),
        [_t(input), _t(label)])


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    """Huber-style L1 smoothed below delta (reference smooth_l1_loss)."""
    def f(a, b):
        d = a - b.astype(a.dtype)
        ad = jnp.abs(d)
        val = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
        return _reduce(val, reduction)
    return dispatch.call("smooth_l1_loss", f, [_t(input), _t(label)])


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    """BCE over probabilities with optional weight (reference
    binary_cross_entropy)."""
    inputs = [_t(input), _t(label)]
    has_w = weight is not None
    if has_w:
        inputs.append(_t(weight))

    def f(p, y, *w):
        p = jnp.clip(p, 1e-12, 1 - 1e-7)
        val = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if has_w:
            val = val * w[0]
        return _reduce(val, reduction)
    return dispatch.call("binary_cross_entropy", f, inputs,
                         differentiable_mask=[True, True] + [False] * has_w)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    """Numerically stable BCE straight from logits (reference
    binary_cross_entropy_with_logits)."""
    inputs = [_t(logit), _t(label)]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        inputs.append(_t(weight))
    if has_pw:
        inputs.append(_t(pos_weight))

    def f(x, y, *rest):
        y = y.astype(x.dtype)
        max_val = jnp.maximum(-x, 0)
        if has_pw:
            pw = rest[-1]
            log_weight = (pw - 1) * y + 1
            loss = (1 - y) * x + log_weight * (
                jnp.log(jnp.exp(-max_val) + jnp.exp(-x - max_val)) + max_val)
        else:
            loss = (1 - y) * x + max_val + jnp.log(
                jnp.exp(-max_val) + jnp.exp(-x - max_val))
        if has_w:
            loss = loss * rest[0]
        return _reduce(loss, reduction)
    return dispatch.call("bce_with_logits", f, inputs,
                         differentiable_mask=[True, True]
                         + [False] * (has_w + has_pw))


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    """KL divergence sum(target * (log(target) - input)) with input log-probs
    (reference kl_div)."""
    def f(lp, t):
        if log_target:
            val = jnp.exp(t) * (t - lp)
        else:
            tt = jnp.clip(t, 1e-12, None)
            val = t * (jnp.log(tt) - lp)
            val = jnp.where(t > 0, val, 0.0)
        if reduction == "batchmean":
            return jnp.sum(val) / lp.shape[0]
        return _reduce(val, reduction)
    return dispatch.call("kl_div", f, [_t(input), _t(label)])


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    """max(0, -label*(x1-x2) + margin) (reference margin_ranking_loss)."""
    def f(a, b, y):
        return _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction)
    return dispatch.call("margin_ranking_loss", f,
                         [_t(input), _t(other), _t(label)],
                         differentiable_mask=[True, True, False])


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    """Hinge on dissimilar pairs, identity on similar (reference
    hinge_embedding_loss)."""
    def f(a, y):
        val = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(val, reduction)
    return dispatch.call("hinge_embedding_loss", f, [_t(input), _t(label)],
                         differentiable_mask=[True, False])


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    """1 - cos for similar pairs, relu(cos - margin) for dissimilar (reference
    cosine_embedding_loss)."""
    def f(a, b, y):
        cos = (jnp.sum(a * b, axis=-1)
               / jnp.maximum(jnp.linalg.norm(a, axis=-1)
                             * jnp.linalg.norm(b, axis=-1), 1e-12))
        val = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(val, reduction)
    return dispatch.call("cosine_embedding_loss", f,
                         [_t(input1), _t(input2), _t(label)],
                         differentiable_mask=[True, True, False])


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    """max(0, d(a,p) - d(a,n) + margin) over a p-norm metric (reference
    triplet_margin_loss)."""
    def f(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p, axis=-1) ** (1.0 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_an = jnp.minimum(d_an, dist(pos, neg))
        return _reduce(jnp.maximum(0.0, d_ap - d_an + margin), reduction)
    return dispatch.call("triplet_margin_loss", f,
                         [_t(input), _t(positive), _t(negative)])


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    """Focal-modulated BCE with logits for class imbalance (reference
    sigmoid_focal_loss)."""
    inputs = [_t(logit), _t(label)]
    if normalizer is not None:
        inputs.append(_t(normalizer))

    def f(x, y, *n):
        p = jax.nn.sigmoid(x)
        ce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)
    return dispatch.call("sigmoid_focal_loss", f, inputs,
                         differentiable_mask=[True, False]
                         + [False] * (normalizer is not None))


def square_error_cost(input, label):
    """Elementwise (input - label)^2, unreduced (reference square_error_cost).
    """
    return dispatch.call("square_error_cost",
                         lambda a, b: (a - b) ** 2, [_t(input), _t(label)])


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space (reference:
    warpctc binding, python/paddle/nn/functional/loss.py ctc_loss).
    log_probs: (T, N, C) logits."""
    lp, lab = _t(log_probs), _t(labels)
    il, ll = _t(input_lengths), _t(label_lengths)

    def f(logits, labels_, in_len, lab_len):
        T, N, C = logits.shape
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        S = labels_.shape[1]
        ext_len = 2 * S + 1
        # Extended label sequence: blank, l1, blank, l2, ... blank
        ext = jnp.full((N, ext_len), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(labels_.astype(jnp.int32))
        neg_inf = -1e30
        alpha0 = jnp.full((N, ext_len), neg_inf)
        alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0])

        allow_skip = jnp.concatenate([
            jnp.zeros((N, 2), bool),
            ext[:, 2:] != ext[:, :-2]], axis=1) & (jnp.arange(ext_len)[None, :] % 2 == 1)

        def step(alpha, t):
            shifted1 = jnp.concatenate(
                [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            shifted2 = jnp.concatenate(
                [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            shifted2 = jnp.where(allow_skip, shifted2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, shifted1), shifted2)
            emit = jnp.take_along_axis(logp[t], ext, axis=1)
            new_alpha = merged + emit
            new_alpha = jnp.where(t < in_len[:, None], new_alpha, alpha)
            return new_alpha, None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        last = 2 * lab_len.astype(jnp.int32)
        a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
        a_prev = jnp.take_along_axis(
            alpha, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
        ll_total = jnp.logaddexp(a_last, a_prev)
        loss = -ll_total
        if norm_by_times:
            loss = loss / in_len.astype(loss.dtype)
        if reduction == "mean":
            # Reference semantics (loss.py:1977): mean(loss / label_lengths).
            return jnp.mean(loss / jnp.maximum(lab_len, 1).astype(loss.dtype))
        return _reduce(loss, reduction)
    return dispatch.call("ctc_loss", f, [lp, lab, il, ll],
                         differentiable_mask=[True, False, False, False])


__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "nll_loss", "mse_loss",
    "l1_loss", "smooth_l1_loss", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "kl_div", "margin_ranking_loss",
    "hinge_embedding_loss", "cosine_embedding_loss", "triplet_margin_loss",
    "sigmoid_focal_loss", "square_error_cost", "ctc_loss",
]


def log_loss(input, label, epsilon=1e-4, name=None):
    """Negative log likelihood for probabilities (reference ops.yaml
    log_loss)."""
    i, l = _t(input), _t(label)
    def f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(
            1 - p + epsilon)
    return dispatch.call("log_loss", f, [i, l])


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    """True Huber loss (reference ops.yaml huber_loss):
    0.5*d^2 for |d|<delta else delta*(|d| - 0.5*delta). Note this is NOT
    smooth_l1 (which divides by delta)."""
    i, l = _t(input), _t(label)

    def f(a, b):
        d = a - b
        ad = jnp.abs(d)
        out = jnp.where(ad < delta, 0.5 * d * d,
                        delta * (ad - 0.5 * delta))
        if reduction == "mean":
            return jnp.mean(out)
        if reduction == "sum":
            return jnp.sum(out)
        return out
    return dispatch.call("huber_loss", f, [i, l])

__all__ += ['log_loss', 'huber_loss']


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss over a complete binary class tree.

    Default tree (no path_table): leaf for class c is node ``c + K - 1`` in a
    heap-indexed complete binary tree with K-1 internal nodes; walking to the
    root emits one sigmoid decision per internal node. With
    path_table/path_code the custom tree is used (reference
    python/paddle/nn/functional/loss.py hsigmoid_loss,
    phi/kernels/cpu/hsigmoid_loss_kernel.cc).
    """
    inp, lab = _t(input), _t(label)
    w = _t(weight)
    tensors = [inp, w, lab]
    diff_mask = [True, True, False]
    has_bias = bias is not None
    if has_bias:
        tensors.append(_t(bias))
        diff_mask.append(True)
    has_table = path_table is not None
    if has_table:
        tensors += [_t(path_table), _t(path_code)]
        diff_mask += [False, False]
    K = num_classes
    depth = max(int(np.ceil(np.log2(max(K, 2)))), 1)  # static: K is python

    def f(x, wt, lab_, *rest):
        bv = rest[0] if has_bias else None
        if has_table:
            nodes = rest[-2].astype(jnp.int64)
            codes = rest[-1].astype(jnp.float32)
            valid = (nodes >= 0).astype(jnp.float32)
            nodes = jnp.maximum(nodes, 0)
        else:
            # default complete binary tree: walk leaf -> root; the tree
            # depth is static so the walk unrolls to `depth` vectorized
            # steps — labels stay on device (the seed built these tables
            # with a host loop over label values, graph-breaking capture)
            i = lab_.reshape(-1).astype(jnp.int64) + (K - 1)
            nd, cd, vd = [], [], []
            for _ in range(depth):
                parent = (i - 1) // 2
                live = i > 0
                nd.append(jnp.where(live, parent, 0))
                cd.append(jnp.where(live & (i == 2 * parent + 1), 1.0, 0.0))
                vd.append(live.astype(jnp.float32))
                i = jnp.where(live, parent, 0)
            nodes = jnp.stack(nd, axis=1)
            codes = jnp.stack(cd, axis=1)
            valid = jnp.stack(vd, axis=1)
        wsel = wt[nodes]                      # (B, D, F)
        logits = jnp.einsum("bdf,bf->bd", wsel, x)
        if bv is not None:
            logits = logits + bv.reshape(-1)[nodes]
        # BCE with logits against the path code, masked by path validity
        per = (jnp.maximum(logits, 0) - logits * codes
               + jnp.log1p(jnp.exp(-jnp.abs(logits)))) * valid
        return per.sum(axis=1, keepdims=True)

    return dispatch.call("hsigmoid_loss", f, tensors,
                         differentiable_mask=diff_mask)


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance per batch row (reference
    python/paddle/nn/functional/loss.py edit_distance,
    phi/kernels/impl/edit_distance_kernel_impl.h). In-graph DP: the classic
    serial recurrence dp[c] = min(e[c], dp[c-1]+1) unrolls to
    dp[c] = c + min_{k<=c}(e[k]-k), a prefix-min (lax.cummin) — so each DP
    row is one vectorized step and the whole metric is a vmapped fori_loop
    XLA compiles into the caller's program (the seed version pulled the
    operands to the host and graph-broke to_static capture; tpulint TPU1xx).

    Returns (distance (B,1) float, sequence_num (1,) int).
    """
    it, lt = _t(input), _t(label)
    m_pad, n_pad = int(it.shape[1]), int(lt.shape[1])
    ign = tuple(sorted(set(ignored_tokens or ())))
    tensors = [it, lt]
    has_il, has_ll = input_length is not None, label_length is not None
    if has_il:
        tensors.append(_t(input_length))
    if has_ll:
        tensors.append(_t(label_length))

    def f(a, b, *rest):
        il = rest[0].reshape(-1) if has_il else jnp.full(
            (a.shape[0],), m_pad, jnp.int32)
        ll = rest[-1].reshape(-1) if has_ll else jnp.full(
            (b.shape[0],), n_pad, jnp.int32)

        def compact(seq, length, width):
            # drop ignored tokens in-graph: stable-sort valid entries to
            # the front, padding the tail with -1 (matches no real token)
            keep = jnp.arange(width)[None, :] < length[:, None].astype(
                jnp.int32)
            for tok in ign:
                keep &= seq != tok
            order = jnp.argsort(~keep, axis=1, stable=True)
            packed = jnp.where(jnp.take_along_axis(keep, order, axis=1),
                               jnp.take_along_axis(seq, order, axis=1), -1)
            return packed, keep.sum(axis=1)

        s1, m_eff = compact(a, il, m_pad)
        s2, n_eff = compact(b, ll, n_pad)

        def row_distance(x, y, m, n):
            cols = jnp.arange(n_pad + 1, dtype=jnp.int32)

            def step(r, carry):
                prev, best = carry
                cost = (x[r - 1] != y).astype(jnp.int32)
                # e[c] = min(delete, substitute); insert handled below
                e = jnp.minimum(prev[1:] + 1, prev[:-1] + cost)
                g = jnp.concatenate([jnp.full((1,), r, jnp.int32), e])
                dp = jax.lax.cummin(g - cols) + cols
                best = jnp.where(r == m, dp[n], best)
                return dp, best

            dp0 = cols
            best0 = jnp.where(m == 0, dp0[n], 0)
            _, best = jax.lax.fori_loop(1, m_pad + 1, step, (dp0, best0))
            return best

        dist = jax.vmap(row_distance)(s1, s2, m_eff, n_eff).astype(
            jnp.float32)
        if normalized:
            dist = dist / jnp.maximum(n_eff, 1).astype(jnp.float32)
        return dist.reshape(-1, 1), jnp.full((1,), a.shape[0], jnp.int32)

    return dispatch.call("edit_distance", f, tensors, multi_output=True,
                         differentiable_mask=[False] * len(tensors),
                         export_attrs={"normalized": normalized,
                                       "ignored_tokens": ign})


def ctc_align(input, input_length=None, blank=0, padding_value=0, name=None):
    """CTC greedy alignment: merge repeats then drop blanks
    (reference ctc_align op, phi/kernels/cpu/ctc_align_kernel.cc).
    input: (B, T) argmax token ids.

    Deliberately host-side: the output WIDTH is data-dependent (longest
    de-blanked row), which XLA's static shapes cannot express — a decode
    utility, never on the training path."""
    a = np.asarray(_t(input)._data)  # tpulint: disable=TPU104 — dynamic output shape forces host decode
    il = (np.asarray(_t(input_length)._data).ravel()  # tpulint: disable=TPU104 — same host decode path
          if input_length is not None else
          np.full(a.shape[0], a.shape[1], np.int64))
    rows, lens = [], []
    for i in range(a.shape[0]):
        seq = a[i, :il[i]]
        prev = None
        out = []
        for tkn in seq.tolist():  # tpulint: disable=TPU102 — host decode, see docstring
            if tkn != prev and tkn != blank:
                out.append(tkn)
            prev = tkn
        rows.append(out)
        lens.append(len(out))
    width = max(max(lens, default=0), 1)
    res = np.full((a.shape[0], width), padding_value, dtype=a.dtype)
    for i, r in enumerate(rows):
        res[i, :len(r)] = r
    return (Tensor(jnp.asarray(res)),
            Tensor(jnp.asarray(lens, dtype=jnp.int32)))


def rnnt_loss(logits, labels, logit_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss (reference warprnnt op,
    phi/kernels/impl/warprnnt_kernel_impl.h; paddle.nn.functional.rnnt_loss).

    logits: (B, T, U+1, V) unnormalized; labels: (B, U) int. TPU-native: the
    alpha recursion runs as U+1 vectorized row updates (each a lax-style
    cumulative band update over T), fully differentiable by jax.vjp — no
    hand-written backward, no warp-rnnt CUDA.
    """
    lg, lb = _t(logits), _t(labels)
    tlt, ult = _t(logit_lengths), _t(label_lengths)

    def f_all(lp, lab_in, tl_in, ul_in):
        B, T, U1, V = lp.shape
        tl = tl_in.reshape(-1)
        ul = ul_in.reshape(-1)
        logp = jax.nn.log_softmax(lp, axis=-1)
        blank_lp = logp[..., blank]
        lab = lab_in.astype(jnp.int64)
        emit_lp = jnp.take_along_axis(
            logp[:, :, :U1 - 1, :], lab[:, None, :, None], axis=-1)[..., 0]
        if fastemit_lambda:
            # FastEmit (arXiv:2010.11148): scale the gradient through emit
            # terms by (1 + lambda) without changing the loss value — the
            # identity x + l*(x - stop_grad(x)) adds 0 forward, scales vjp
            emit_lp = emit_lp + fastemit_lambda * (
                emit_lp - jax.lax.stop_gradient(emit_lp))
        NEG = -1e30
        tmask = jnp.arange(T)[None, :] < tl[:, None]
        alpha0 = jnp.concatenate(
            [jnp.zeros((B, 1)), jnp.cumsum(blank_lp[:, :-1, 0], axis=1)],
            axis=1)
        alpha0 = jnp.where(tmask, alpha0, NEG)
        rows = [alpha0]
        for u in range(1, U1):
            start = rows[-1] + emit_lp[:, :, u - 1]
            bl_u = blank_lp[:, :, u]

            def t_step(carry, t, start=start, bl_u=bl_u):
                cur = jnp.logaddexp(start[:, t], carry + bl_u[:, t - 1])
                return cur, cur

            first = start[:, 0]
            _, rest = jax.lax.scan(t_step, first, jnp.arange(1, T))
            au = jnp.concatenate([first[:, None], rest.T], axis=1)
            au = jnp.where(tmask, au, NEG)
            rows.append(au)
        A = jnp.stack(rows, axis=2)                     # (B, T, U1)
        tb = tl - 1
        ub = ul
        binx = jnp.arange(B)
        ll = A[binx, tb, ub] + blank_lp[binx, tb, ub]
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return dispatch.call("rnnt_loss", f_all, [lg, lb, tlt, ult],
                         differentiable_mask=[True, False, False, False])


__all__ += ['hsigmoid_loss', 'edit_distance', 'ctc_align', 'rnnt_loss']


def fused_linear_cross_entropy(x, weight, label, bias=None,
                               transpose_y=False, ignore_index=-100,
                               reduction="mean", chunk_rows=4096):
    """Cross entropy of ``x @ weight (+ bias)`` against hard ``label``
    WITHOUT materializing the full ``(N, V)`` logits tensor.

    TPU-native fusion of the LM-head matmul with the loss (the reference
    computes them as two ops — ``matmul`` then ``cross_entropy_with_softmax``
    — which forces the ``(batch*seq, vocab)`` logits through HBM twice in
    forward and again in backward). Here the rows are processed in
    ``chunk_rows`` slices under ``jax.lax.scan``; each slice's logits are
    a transient and are REcomputed inside backward (``jax.checkpoint``), so
    peak memory is ``O(chunk_rows * V)`` and the logits never round-trip
    HBM between ops. The streaming max/lse accumulate in f32 while the
    matmul stays in the input dtype (bf16 under AMP).

    Args follow ``cross_entropy``; ``x`` is ``(N, H)`` (callers flatten
    batch/seq), ``weight`` is ``(H, V)`` (or ``(V, H)`` with
    ``transpose_y=True`` for embedding-tied heads), ``label`` is ``(N,)``.
    ``reduction`` in {"mean", "sum", "none"}; mean averages over
    non-ignored rows.
    """
    x, weight, label = _t(x), _t(weight), _t(label)
    inputs = [x, weight, label]
    has_b = bias is not None
    if has_b:
        inputs.append(_t(bias))

    def f(xa, wa, lab, *b):
        n, h = xa.shape
        if n == 0:      # e.g. seq_len==1 -> empty shifted labels
            if reduction == "none":
                return jnp.zeros((0,), jnp.float32)
            return jnp.asarray(0.0, jnp.float32)
        chunk = min(chunk_rows, n)
        pad = (-n) % chunk
        if pad:
            xa = jnp.concatenate(
                [xa, jnp.zeros((pad, h), xa.dtype)], axis=0)
            lab = jnp.concatenate(
                [lab, jnp.full((pad,), ignore_index, lab.dtype)], axis=0)
        n_chunks = xa.shape[0] // chunk
        xc = xa.reshape(n_chunks, chunk, h)
        lc = lab.reshape(n_chunks, chunk)

        def chunk_nll(x_c, l_c):
            logits = (x_c @ wa.T) if transpose_y else (x_c @ wa)
            if has_b:
                logits = logits + b[0]
            m = jax.lax.stop_gradient(
                jnp.max(logits, axis=-1, keepdims=True))
            shifted = (logits - m).astype(jnp.float32)
            lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) \
                + jnp.squeeze(m.astype(jnp.float32), -1)
            l_i = l_c.astype(jnp.int32)
            valid = l_i != ignore_index
            safe = jnp.where(valid, l_i, 0)
            picked = jnp.squeeze(jnp.take_along_axis(
                logits, safe[:, None], axis=-1), -1)
            nll = jnp.where(valid, lse - picked.astype(jnp.float32), 0.0)
            return nll, valid

        chunk_nll = jax.checkpoint(chunk_nll)

        def body(carry, xl):
            s, c = carry
            nll, valid = chunk_nll(*xl)
            return (s + jnp.sum(nll), c + jnp.sum(valid)), \
                (nll if reduction == "none" else None)

        (total, count), per_row = jax.lax.scan(
            body, (jnp.asarray(0.0, jnp.float32),
                   jnp.asarray(0, jnp.int32)), (xc, lc))
        if reduction == "none":
            return per_row.reshape(-1)[:n]
        if reduction == "sum":
            return total
        return total / jnp.maximum(count, 1)

    return dispatch.call("fused_linear_cross_entropy", f, inputs,
                         differentiable_mask=[True, True, False]
                         + [True] * has_b)


__all__ += ['fused_linear_cross_entropy']
