"""nn.functional tail: the remaining reference functional surface.

Reference parity: python/paddle/nn/functional/{loss,distance,common,
activation,flash_attention}.py entries present in the reference
``nn.functional.__all__`` but previously absent here. Formulas follow
the cited reference implementations; everything is jnp through the
standard dispatch (XLA fuses, lazy vjp differentiates).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import dispatch
from ...core.tensor import Tensor, as_tensor

__all__ = [
    "pairwise_distance", "dice_loss", "npair_loss", "poisson_nll_loss",
    "multi_label_soft_margin_loss", "soft_margin_loss",
    "multi_margin_loss", "gaussian_nll_loss",
    "triplet_margin_with_distance_loss", "adaptive_log_softmax_with_loss",
    "margin_cross_entropy", "sparse_attention", "flash_attn_qkvpacked",
    "flash_attn_varlen_qkvpacked", "elu_", "hardtanh_", "leaky_relu_",
    "relu_", "softmax_", "tanh_", "thresholded_relu_",
]


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _reduce(loss, reduction):
    from ... import ops
    if reduction == "mean":
        return ops.mean(loss)
    if reduction == "sum":
        return ops.sum(loss)
    if reduction == "none":
        return loss
    raise ValueError(
        f"reduction should be 'mean'/'sum'/'none', got {reduction!r}")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """reference distance.py pairwise_distance: ||x - y + eps||_p."""
    def f(a, b):
        d = a - b + epsilon
        out = (jnp.abs(d) ** p).sum(-1) ** (1.0 / p)
        return out[..., None] if keepdim else out
    return dispatch.call("pairwise_distance", f, [_t(x), _t(y)])


def dice_loss(input, label, epsilon=1e-5, name=None):
    """reference loss.py dice_loss (softmaxed input, int label)."""
    def f(a, lb):
        one_hot = jax.nn.one_hot(lb.squeeze(-1), a.shape[-1],
                                 dtype=a.dtype)
        axes = tuple(range(1, a.ndim))
        inse = (a * one_hot).sum(axes)
        denom = a.sum(axes) + one_hot.sum(axes)
        return (1 - 2 * inse / (denom + epsilon)).mean()
    return dispatch.call("dice_loss", f, [_t(input), _t(label)],
                         differentiable_mask=[True, False])


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """reference loss.py npair_loss (NIPS 2016 n-pair loss)."""
    def f(a, p, lb):
        n = a.shape[0]
        lb = lb.reshape(n, 1).astype(jnp.float32)
        same = (lb == lb.T).astype(jnp.float32)
        same = same / same.sum(1, keepdims=True)
        l2 = ((a * a).sum(1).mean() + (p * p).sum(1).mean()) \
            * 0.25 * l2_reg
        sim = a @ p.T
        logp = jax.nn.log_softmax(sim, axis=-1)
        ce = (-(same * logp).sum(-1))
        return l2 + ce.mean()
    return dispatch.call("npair_loss", f,
                         [_t(anchor), _t(positive), _t(labels)],
                         differentiable_mask=[True, True, False])


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    """reference loss.py poisson_nll_loss."""
    def f(a, y):
        if log_input:
            loss = jnp.exp(a) - y * a
        else:
            loss = a - y * jnp.log(a + epsilon)
        if full:
            stirling = y * jnp.log(y) - y + 0.5 * jnp.log(2 * math.pi * y)
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return loss
    out = dispatch.call("poisson_nll_loss", f, [_t(input), _t(label)],
                        differentiable_mask=[True, False])
    return _reduce(out, reduction)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    """reference loss.py: per-class BCE-with-logits averaged over C."""
    inputs = [_t(input), _t(label)]
    if weight is not None:
        inputs.append(_t(weight))

    def f(a, y, *w):
        term = y * jax.nn.log_sigmoid(a) + (1 - y) * jax.nn.log_sigmoid(-a)
        if w:
            term = term * w[0]
        return -term.mean(-1)
    out = dispatch.call(
        "multi_label_soft_margin_loss", f, inputs,
        differentiable_mask=[True, False] + [False] * (weight is not None))
    return _reduce(out, reduction)


def soft_margin_loss(input, label, reduction="mean", name=None):
    """reference loss.py: log(1 + exp(-label * input))."""
    def f(a, y):
        return jnp.log1p(jnp.exp(-y.astype(a.dtype) * a))
    out = dispatch.call("soft_margin_loss", f, [_t(input), _t(label)],
                        differentiable_mask=[True, False])
    return _reduce(out, reduction)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """reference loss.py multi_margin_loss (multi-class hinge)."""
    inputs = [_t(input), _t(label)]
    if weight is not None:
        inputs.append(_t(weight))

    def f(a, y, *w):
        n, c = a.shape
        x_y = jnp.take_along_axis(a, y[:, None], axis=1)
        m = jnp.maximum(0.0, margin - x_y + a) ** p
        if w:
            m = m * jnp.take(w[0], y)[:, None]
        mask = jax.nn.one_hot(y, c, dtype=a.dtype)
        return ((1 - mask) * m).sum(-1) / c
    out = dispatch.call(
        "multi_margin_loss", f, inputs,
        differentiable_mask=[True, False] + [False] * (weight is not None))
    return _reduce(out, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """reference loss.py gaussian_nll_loss."""
    def f(a, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (a - y) ** 2 / var)
        if full:
            loss = loss + 0.5 * math.log(2 * math.pi)
        return loss
    out = dispatch.call("gaussian_nll_loss", f,
                        [_t(input), _t(label), _t(variance)],
                        differentiable_mask=[True, False, True])
    return _reduce(out, reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """reference loss.py: hinge on custom-distance triplets."""
    dist = distance_function or (
        lambda a, b: pairwise_distance(a, b))
    d_pos = _t(dist(_t(input), _t(positive)))
    d_neg = _t(dist(_t(input), _t(negative)))
    if swap:
        from ... import ops
        d_swap = _t(dist(_t(positive), _t(negative)))
        d_neg = ops.minimum(d_neg, d_swap)

    def f(dp, dn):
        return jnp.maximum(dp - dn + margin, 0.0)
    out = dispatch.call("triplet_margin_with_distance_loss", f,
                        [d_pos, d_neg])
    return _reduce(out, reduction)


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """reference loss.py adaptive_log_softmax_with_loss (Grave et al.
    efficient softmax): head covers the frequent classes + one slot per
    tail cluster; each tail cluster gets a projected softmax. Returns
    (per-sample logprob of the target, mean NLL loss)."""
    inputs = [_t(input), _t(label), _t(head_weight)]
    tails = [( _t(w1), _t(w2)) for (w1, w2) in tail_weights]
    for w1, w2 in tails:
        inputs.extend([w1, w2])
    if head_bias is not None:
        inputs.append(_t(head_bias))
    n_tails = len(tails)
    cutoffs = [int(c) for c in cutoffs]
    shortlist = cutoffs[0]

    def f(x, y, hw, *rest):
        tw = [(rest[2 * i], rest[2 * i + 1]) for i in range(n_tails)]
        hb = rest[2 * n_tails] if len(rest) > 2 * n_tails else None
        head_logits = x @ hw
        if hb is not None:
            head_logits = head_logits + hb
        head_logp = jax.nn.log_softmax(head_logits, axis=-1)
        # shortlist targets read the head directly
        out = jnp.take_along_axis(
            head_logp, jnp.clip(y, 0, shortlist - 1)[:, None],
            axis=1)[:, 0]
        for i, (lo, hi) in enumerate(zip(cutoffs[:-1], cutoffs[1:])):
            in_cluster = (y >= lo) & (y < hi)
            proj = x @ tw[i][0]            # [n, d_proj]
            cl_logits = proj @ tw[i][1]    # [n, cluster_size]
            cl_logp = jax.nn.log_softmax(cl_logits, axis=-1)
            rel = jnp.clip(y - lo, 0, hi - lo - 1)
            cl_score = head_logp[:, shortlist + i] + jnp.take_along_axis(
                cl_logp, rel[:, None], axis=1)[:, 0]
            out = jnp.where(in_cluster, cl_score, out)
        return out, -out.mean()

    mask = [True, False, True] + [True] * (2 * n_tails) \
        + ([True] if head_bias is not None else [])
    return dispatch.call("adaptive_log_softmax_with_loss", f, inputs,
                         differentiable_mask=mask)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean",
                         name=None):
    """reference loss.py margin_cross_entropy (ArcFace family): the
    target cosine is re-margined cos(m1·θ + m2) − m3 before scaling.
    Single-group form (the TP-sharded variant rides ParallelCrossEntropy).
    """
    def f(cos, y):
        n, c = cos.shape
        theta = jnp.arccos(jnp.clip(cos, -1.0 + 1e-7, 1.0 - 1e-7))
        target_cos = jnp.cos(margin1 * theta + margin2) - margin3
        one_hot = jax.nn.one_hot(y, c, dtype=cos.dtype)
        out = jnp.where(one_hot > 0, target_cos, cos) * scale
        logp = jax.nn.log_softmax(out, axis=-1)
        loss = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
        return loss, jnp.exp(logp)
    loss, softmax = dispatch.call(
        "margin_cross_entropy", f, [_t(logits), _t(label)],
        differentiable_mask=[True, False])
    loss = _reduce(loss, reduction) if reduction else loss
    return (loss, softmax) if return_softmax else loss


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """reference sparse_attention (block-sparse attention over a CSR
    connectivity pattern; reference gates it to CUDA 11+, here it is a
    gather-based XLA lowering): q/k/v are [B, H, S, D], offsets/columns
    describe per-row attended positions."""
    q, k, v = _t(query), _t(key), _t(value)
    off, cols = _t(sparse_csr_offset), _t(sparse_csr_columns)

    # dense computation masked to the CSR pattern (numerically identical
    # to the reference's block-sparse kernel; XLA fuses mask + softmax)
    def dense(qa, ka, va, offa, colsa):
        b, h, s, d = qa.shape
        scores = jnp.einsum("bhsd,bhtd->bhst", qa, ka) / math.sqrt(d)
        total = colsa.shape[-1]
        width = total // s
        cols2 = colsa.reshape(b, h, s, width).astype(jnp.int32)
        mask = jnp.zeros((b, h, s, s), bool)
        rows = jnp.arange(s)[None, None, :, None]
        mask = mask.at[
            jnp.arange(b)[:, None, None, None],
            jnp.arange(h)[None, :, None, None],
            jnp.broadcast_to(rows, cols2.shape),
            cols2].set(True)
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        probs = jnp.where(mask, probs, 0.0)
        return jnp.einsum("bhst,bhtd->bhsd", probs, va)
    return dispatch.call("sparse_attention", dense, [q, k, v, off, cols],
                         differentiable_mask=[True, True, True, False,
                                              False])


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, *, training=True,
                         name=None):
    """reference flash_attn_qkvpacked: qkv [B, S, 3, H, D] → attention
    (routes through the flash/XLA crossover like flash_attention)."""
    from .flash_attention import flash_attention
    from ... import ops
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax,
                           training=training)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale,
                                dropout=0.0, causal=False,
                                return_softmax=False, *, training=True,
                                name=None):
    """reference flash_attn_varlen_qkvpacked over packed
    [total_tokens, 3, H, D]."""
    from .flash_attention import flash_attn_unpadded
    q = qkv[:, 0]
    k = qkv[:, 1]
    v = qkv[:, 2]
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale,
                               dropout=dropout, causal=causal,
                               return_softmax=return_softmax,
                               training=training)


# -------------------------------------------------- in-place activations
def _act_inplace(name, base_getter):
    def fn(x, *args, **kwargs):
        out = base_getter()(x, *args, **kwargs)
        x._swap_payload(out._data)
        x.grad_node = out.grad_node
        x.output_index = getattr(out, "output_index", 0)
        x.stop_gradient = out.stop_gradient
        return x
    fn.__name__ = name
    fn.__doc__ = (f"In-place variant of nn.functional.{name[:-1]} "
                  f"(payload swap + grad-link adoption).")
    return fn


def _mk(name):
    def getter():
        from .. import functional as F
        return getattr(F, name)
    return getter


elu_ = _act_inplace("elu_", _mk("elu"))
hardtanh_ = _act_inplace("hardtanh_", _mk("hardtanh"))
leaky_relu_ = _act_inplace("leaky_relu_", _mk("leaky_relu"))
relu_ = _act_inplace("relu_", _mk("relu"))
softmax_ = _act_inplace("softmax_", _mk("softmax"))
tanh_ = _act_inplace("tanh_", _mk("tanh"))
thresholded_relu_ = _act_inplace("thresholded_relu_",
                                 _mk("thresholded_relu"))
