"""Vision functionals: affine_grid / grid_sample / temporal_shift.

Reference: python/paddle/nn/functional/vision.py (affine_grid:28,
grid_sample:237, channel_shuffle lives in common here), phi kernels
paddle/phi/kernels/impl/affine_grid_kernel_impl.h, gpu/grid_sample_kernel.cu,
gpu/temporal_shift_kernel.cu. TPU-native: the sampler is a pair of gathers +
elementwise lerps that XLA fuses into one kernel; everything is static-shape
and fully differentiable through ``dispatch.call`` (jax.vjp), so
``grid_sample`` backprops to both the input feature map and the grid — same
contract as the reference CUDA kernels.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core import dispatch
from ...core.tensor import Tensor, as_tensor

__all__ = ["affine_grid", "grid_sample", "temporal_shift"]


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _affine_base_grid(n, h, w, align_corners, dtype):
    if align_corners:
        xs = jnp.linspace(-1.0, 1.0, w, dtype=dtype)
        ys = jnp.linspace(-1.0, 1.0, h, dtype=dtype)
    else:
        xs = (jnp.arange(w, dtype=dtype) * 2 + 1) / w - 1
        ys = (jnp.arange(h, dtype=dtype) * 2 + 1) / h - 1
    gx, gy = jnp.meshgrid(xs, ys)  # (h, w)
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)  # (h, w, 3)
    return jnp.broadcast_to(base, (n, h, w, 3))


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Generate a 2D/3D sampling grid from batched affine matrices.

    theta: (N, 2, 3) for 2D -> grid (N, H, W, 2);
           (N, 3, 4) for 3D -> grid (N, D, H, W, 3).
    Reference: python/paddle/nn/functional/vision.py:28.
    """
    theta = _t(theta)
    shape = [int(s) for s in out_shape]

    def f(th):
        dtype = th.dtype
        if th.shape[-2:] == (2, 3):
            n, _, h, w = shape
            base = _affine_base_grid(n, h, w, align_corners, dtype)
            # (n,h,w,3) @ (n,3,2) -> (n,h,w,2); highest precision — grid
            # coords feed a sampler, bf16 MXU rounding visibly blurs output
            return jnp.einsum("nhwk,nck->nhwc", base, th,
                              precision="highest")
        n, _, d, h, w = shape
        if align_corners:
            def axis(sz):
                return jnp.linspace(-1.0, 1.0, sz, dtype=dtype)
        else:
            def axis(sz):
                return (jnp.arange(sz, dtype=dtype) * 2 + 1) / sz - 1
        gz, gy, gx = jnp.meshgrid(axis(d), axis(h), axis(w), indexing="ij")
        base = jnp.stack([gx, gy, gz, jnp.ones_like(gx)], axis=-1)
        base = jnp.broadcast_to(base, (n, d, h, w, 4))
        return jnp.einsum("ndhwk,nck->ndhwc", base, th, precision="highest")

    return dispatch.call("affine_grid", f, [theta])


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1) * 0.5 * (size - 1)
    return ((coord + 1) * size - 1) * 0.5


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample ``x`` (N,C,H,W) at normalized ``grid`` (N,Hg,Wg,2) locations.

    grid[..., 0] is x (width) in [-1, 1], grid[..., 1] is y (height).
    Modes: bilinear | nearest. Padding: zeros | border | reflection.
    Reference: python/paddle/nn/functional/vision.py:237,
    paddle/phi/kernels/gpu/grid_sample_kernel.cu.
    """
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample mode must be bilinear|nearest, got {mode}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"unsupported padding_mode {padding_mode}")
    x, grid = _t(x), _t(grid)

    def f(a, g):
        n, c, h, w = a.shape
        gdt = g.dtype
        ix = _unnormalize(g[..., 0], w, align_corners)
        iy = _unnormalize(g[..., 1], h, align_corners)

        def reflect(coord, size):
            # reference reflects about pixel centers (align) or borders
            if align_corners:
                span = 2 * (size - 1)
                if span == 0:
                    return jnp.zeros_like(coord)
                coord = jnp.abs(coord) % span
                return jnp.where(coord > size - 1, span - coord, coord)
            span = 2 * size
            coord = jnp.abs(coord + 0.5) % span
            coord = jnp.where(coord > size, span - coord, coord)
            return jnp.clip(coord - 0.5, 0, size - 1)

        if padding_mode == "border":
            ix = jnp.clip(ix, 0, w - 1)
            iy = jnp.clip(iy, 0, h - 1)
        elif padding_mode == "reflection":
            ix = reflect(ix, w)
            iy = reflect(iy, h)

        def gather(yi, xi):
            # (n, hg, wg) integer coords -> (n, c, hg, wg) values
            yi_c = jnp.clip(yi, 0, h - 1)
            xi_c = jnp.clip(xi, 0, w - 1)
            batch = jnp.arange(n).reshape(n, 1, 1)
            vals = a[batch, :, yi_c, xi_c]          # (n, hg, wg, c)
            if padding_mode == "zeros":
                ok = ((yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1))
                vals = vals * ok[..., None].astype(vals.dtype)
            return jnp.moveaxis(vals, -1, 1)        # (n, c, hg, wg)

        if mode == "nearest":
            return gather(jnp.round(iy).astype(jnp.int32),
                          jnp.round(ix).astype(jnp.int32))

        x0 = jnp.floor(ix)
        y0 = jnp.floor(iy)
        x1, y1 = x0 + 1, y0 + 1
        wx1 = (ix - x0).astype(gdt)
        wy1 = (iy - y0).astype(gdt)
        wx0, wy0 = 1 - wx1, 1 - wy1
        x0i, x1i = x0.astype(jnp.int32), x1.astype(jnp.int32)
        y0i, y1i = y0.astype(jnp.int32), y1.astype(jnp.int32)
        out = (gather(y0i, x0i) * (wy0 * wx0)[:, None]
               + gather(y0i, x1i) * (wy0 * wx1)[:, None]
               + gather(y1i, x0i) * (wy1 * wx0)[:, None]
               + gather(y1i, x1i) * (wy1 * wx1)[:, None])
        return out

    return dispatch.call("grid_sample", f, [x, grid])


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM temporal shift: roll a channel slice one step along time.

    x: (N*T, C, H, W) with T=seg_num. The first ``shift_ratio`` of channels
    shifts backward in time, the next ``shift_ratio`` forward, rest unchanged.
    Reference: paddle/phi/kernels/gpu/temporal_shift_kernel.cu,
    python/paddle/nn/functional/extension.py temporal_shift.
    """
    x = _t(x)
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"unsupported data_format {data_format}")

    def f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        t = seg_num
        n = nt // t
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        v = a.reshape(n, t, c, h, w)
        pad = jnp.zeros((n, 1, c, h, w), dtype=a.dtype)
        back = jnp.concatenate([v[:, 1:, :c1], pad[:, :, :c1]], axis=1)
        fwd = jnp.concatenate([pad[:, :, c1:c2], v[:, :-1, c1:c2]], axis=1)
        out = jnp.concatenate([back, fwd, v[:, :, c2:]], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return dispatch.call("temporal_shift", f, [x])
