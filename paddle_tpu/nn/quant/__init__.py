"""paddle.nn.quant — quantization layer/functional namespace (reference:
python/paddle/nn/quant/ — quantized functional ops and layers; here they
re-export the TPU-native quantization implementations)."""
from ...quantization import (QAT, PTQ, QuantConfig, QuantedLinear,
                             fake_quant, llm_int8_linear,
                             weight_dequantize, weight_only_linear,
                             weight_quantize)

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear", "fake_quant", "QuantConfig", "QuantedLinear",
           "PTQ", "QAT"]
