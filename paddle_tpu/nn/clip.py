"""Gradient clipping strategies.

Reference: python/paddle/nn/clip.py — ClipGradByValue, ClipGradByNorm,
ClipGradByGlobalNorm. These objects are handed to optimizers
(``grad_clip=``) and applied to (param, grad) lists before the update. The
hybrid-parallel variant that allreduces the global norm across mesh axes
lives in paddle_tpu.distributed.fleet (reference:
hybrid_parallel_optimizer.py HybridParallelClipGrad).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            ng = dispatch.call("clip", lambda a: jnp.clip(a, self.min, self.max), [g])
            out.append((p, ng))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue

            def f(a):
                norm = jnp.sqrt(jnp.sum(a.astype(jnp.float32) ** 2))
                scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                    1.0)
                return (a.astype(jnp.float32) * scale).astype(a.dtype)
            out.append((p, dispatch.call("clip_by_norm", f, [g])))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name
        self.auto_skip_clip = auto_skip_clip

    def _global_norm(self, grads):
        sq = None
        for g in grads:
            s = dispatch.call(
                "sq_l2", lambda a: jnp.sum(a.astype(jnp.float32) ** 2), [g])
            sq = s if sq is None else sq + s
        return dispatch.call("sqrt_", lambda a: jnp.sqrt(a), [sq])

    def _dygraph_clip(self, params_grads):
        grads = [g for p, g in params_grads
                 if g is not None and getattr(p, "need_clip", True)]
        if not grads:
            return params_grads
        global_norm = self._global_norm(grads)

        def scale_fn(a, n):
            s = self.clip_norm / jnp.maximum(n, self.clip_norm)
            return (a.astype(jnp.float32) * s).astype(a.dtype)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, dispatch.call("global_norm_scale", scale_fn,
                                         [g, global_norm])))
        return out


GradientClipBase = ClipGradBase
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm
