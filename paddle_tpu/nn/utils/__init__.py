"""nn.utils (reference: python/paddle/nn/utils/)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core import dispatch
from ...core.tensor import Tensor


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = None
        for g in grads:
            m = dispatch.call("absmax", lambda a: jnp.max(jnp.abs(a)), [g])
            total = m if total is None else dispatch.call(
                "maximum", lambda a, b: jnp.maximum(a, b), [total, m])
    else:
        acc = None
        for g in grads:
            s = dispatch.call(
                "norm_pow", lambda a: jnp.sum(jnp.abs(a.astype(jnp.float32))
                                              ** norm_type), [g])
            acc = s if acc is None else acc + s
        total = dispatch.call("norm_root",
                              lambda a: a ** (1.0 / norm_type), [acc])
    # stays ON DEVICE: no host sync mid-step (the reference's CUDA path
    # also keeps the coef on device); min(1, max/total) folds the branch
    if error_if_nonfinite:
        import numpy as np
        if not np.isfinite(float(total.numpy())):  # tpulint: disable=TPU101 — error_if_nonfinite contract (torch parity) requires the host check before scaling
            raise RuntimeError(
                f"the total norm of gradients is non-finite; disable with "
                f"error_if_nonfinite=False")
    coef = dispatch.call(
        "clip_coef",
        lambda t: jnp.minimum(1.0, max_norm / (t + 1e-6)), [total])
    for p in parameters:
        if p.grad is not None:
            g = p.grad._data
            p.grad._swap_payload(g * coef._data.astype(g.dtype))
    return total


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._swap_payload(jnp.clip(p.grad._data, -clip_value,
                                          clip_value))


def parameters_to_vector(parameters, name=None):
    from ...ops import manipulation
    return manipulation.concat(
        [manipulation.reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p.set_value(vec._data[offset:offset + n].reshape(p._data.shape))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize ``layer.<name>`` as direction*magnitude (reference:
    python/paddle/nn/utils/weight_norm_hook.py — weight = g * v/||v||,
    recomputed by a forward-pre-hook each call)."""
    import numpy as np

    from ...ops import linalg  # noqa: F401  (norm availability)

    w = getattr(layer, name)
    if w is None:
        raise ValueError(f"layer has no parameter {name!r}")
    axes = tuple(i for i in range(w._data.ndim) if i != dim)

    def _norm(arr):
        return jnp.sqrt(jnp.sum(arr.astype(jnp.float32) ** 2, axis=axes,
                                keepdims=True)).astype(arr.dtype)

    g0 = _norm(w._data)
    from ..parameter import Parameter
    weight_g = Parameter(g0)
    weight_v = Parameter(w._data)
    # replace the original parameter; keep `name` as a plain attribute
    # recomputed before every forward
    del layer._parameters[name]
    layer.add_parameter(name + "_g", weight_g)
    layer.add_parameter(name + "_v", weight_v)

    def compute(layer_, inputs=None):
        v = getattr(layer_, name + "_v")
        g = getattr(layer_, name + "_g")
        normed = dispatch.call(
            "weight_norm", lambda va, ga: ga * va / (_norm(va) + 1e-12),
            [v, g])
        object.__setattr__(layer_, name, normed)

    compute(layer)
    hook = layer.register_forward_pre_hook(
        lambda layer_, inputs: compute(layer_))
    layer._weight_norm_hooks = getattr(layer, "_weight_norm_hooks", {})
    layer._weight_norm_hooks[name] = hook
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a single parameter and remove the hook."""
    hooks = getattr(layer, "_weight_norm_hooks", {})
    hook = hooks.pop(name, None)
    if hook is None:
        raise ValueError(f"no weight_norm applied to parameter {name!r}")
    hook.remove()
    from ..parameter import Parameter
    w = getattr(layer, name)  # last computed normalized weight
    if name in layer.__dict__:
        del layer.__dict__[name]
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    layer.add_parameter(name, Parameter(w._data))
    return layer
