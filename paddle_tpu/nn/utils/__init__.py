"""nn.utils (reference: python/paddle/nn/utils/)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core import dispatch
from ...core.tensor import Tensor


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = None
        for g in grads:
            m = dispatch.call("absmax", lambda a: jnp.max(jnp.abs(a)), [g])
            total = m if total is None else dispatch.call(
                "maximum", lambda a, b: jnp.maximum(a, b), [total, m])
    else:
        acc = None
        for g in grads:
            s = dispatch.call(
                "norm_pow", lambda a: jnp.sum(jnp.abs(a.astype(jnp.float32))
                                              ** norm_type), [g])
            acc = s if acc is None else acc + s
        total = dispatch.call("norm_root",
                              lambda a: a ** (1.0 / norm_type), [acc])
    clip_coef = max_norm / (float(total.numpy()) + 1e-6)
    if clip_coef < 1:
        for p in parameters:
            if p.grad is not None:
                p.grad._swap_payload(p.grad._data * clip_coef)
    return total


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._swap_payload(jnp.clip(p.grad._data, -clip_value,
                                          clip_value))


def parameters_to_vector(parameters, name=None):
    from ...ops import manipulation
    return manipulation.concat(
        [manipulation.reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p.set_value(vec._data[offset:offset + n].reshape(p._data.shape))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    return layer


def remove_weight_norm(layer, name="weight"):
    return layer
