"""GoogLeNet (Inception v1) and InceptionV3 (reference:
python/paddle/vision/models/{googlenet,inceptionv3}.py). Inception branches
are independent convs XLA runs as one fused graph; concat on channel axis."""
from __future__ import annotations

from ... import nn


def _conv_bn(in_c, out_c, kernel, stride=1, padding=0):
    return nn.Sequential(
        nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=padding,
                  bias_attr=False),
        nn.BatchNorm2D(out_c), nn.ReLU())


def _cat(xs):
    import paddle_tpu as paddle
    return paddle.concat(xs, axis=1)


class _InceptionV1Block(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _conv_bn(in_c, c1, 1)
        self.b3 = nn.Sequential(_conv_bn(in_c, c3r, 1),
                                _conv_bn(c3r, c3, 3, padding=1))
        self.b5 = nn.Sequential(_conv_bn(in_c, c5r, 1),
                                _conv_bn(c5r, c5, 5, padding=2))
        self.proj = nn.Sequential(nn.MaxPool2D(3, 1, padding=1),
                                  _conv_bn(in_c, proj, 1))

    def forward(self, x):
        return _cat([self.b1(x), self.b3(x), self.b5(x), self.proj(x)])


class GoogLeNet(nn.Layer):
    """Reference googlenet.py GoogLeNet; returns (main, aux1, aux2) logits
    like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _conv_bn(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, 2, padding=1),
            _conv_bn(64, 64, 1), _conv_bn(64, 192, 3, padding=1),
            nn.MaxPool2D(3, 2, padding=1))
        self.i3a = _InceptionV1Block(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _InceptionV1Block(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.i4a = _InceptionV1Block(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _InceptionV1Block(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _InceptionV1Block(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _InceptionV1Block(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _InceptionV1Block(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.i5a = _InceptionV1Block(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _InceptionV1Block(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            # aux heads (train-time deep supervision)
            self.aux1 = nn.Sequential(nn.AdaptiveAvgPool2D(4),
                                      _conv_bn(512, 128, 1))
            self.aux1_fc = nn.Sequential(nn.Linear(128 * 16, 1024),
                                         nn.ReLU(), nn.Dropout(0.7),
                                         nn.Linear(1024, num_classes))
            self.aux2 = nn.Sequential(nn.AdaptiveAvgPool2D(4),
                                      _conv_bn(528, 128, 1))
            self.aux2_fc = nn.Sequential(nn.Linear(128 * 16, 1024),
                                         nn.ReLU(), nn.Dropout(0.7),
                                         nn.Linear(1024, num_classes))

    def forward(self, x):
        x = self.pool3(self.i3b(self.i3a(self.stem(x))))
        x = self.i4a(x)
        a1 = x
        x = self.i4d(self.i4c(self.i4b(x)))
        a2 = x
        x = self.pool4(self.i4e(x))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            out = self.fc(self.dropout(x.reshape([x.shape[0], -1])))
            o1 = self.aux1(a1)
            o1 = self.aux1_fc(o1.reshape([o1.shape[0], -1]))
            o2 = self.aux2(a2)
            o2 = self.aux2_fc(o2.reshape([o2.shape[0], -1]))
            return out, o1, o2
        return x


def googlenet(pretrained=False, **kw):
    return GoogLeNet(**kw)


class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_c):
        super().__init__()
        self.b1 = _conv_bn(in_c, 64, 1)
        self.b5 = nn.Sequential(_conv_bn(in_c, 48, 1),
                                _conv_bn(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_conv_bn(in_c, 64, 1),
                                _conv_bn(64, 96, 3, padding=1),
                                _conv_bn(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _conv_bn(in_c, pool_c, 1))

    def forward(self, x):
        return _cat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)])


class _InceptionB(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _conv_bn(in_c, 384, 3, stride=2)
        self.b3d = nn.Sequential(_conv_bn(in_c, 64, 1),
                                 _conv_bn(64, 96, 3, padding=1),
                                 _conv_bn(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return _cat([self.b3(x), self.b3d(x), self.pool(x)])


class _InceptionC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _conv_bn(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _conv_bn(in_c, c7, 1),
            _conv_bn(c7, c7, (1, 7), padding=(0, 3)),
            _conv_bn(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _conv_bn(in_c, c7, 1),
            _conv_bn(c7, c7, (7, 1), padding=(3, 0)),
            _conv_bn(c7, c7, (1, 7), padding=(0, 3)),
            _conv_bn(c7, c7, (7, 1), padding=(3, 0)),
            _conv_bn(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _conv_bn(in_c, 192, 1))

    def forward(self, x):
        return _cat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)])


class _InceptionD(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_conv_bn(in_c, 192, 1),
                                _conv_bn(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _conv_bn(in_c, 192, 1),
            _conv_bn(192, 192, (1, 7), padding=(0, 3)),
            _conv_bn(192, 192, (7, 1), padding=(3, 0)),
            _conv_bn(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return _cat([self.b3(x), self.b7(x), self.pool(x)])


class _InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _conv_bn(in_c, 320, 1)
        self.b3_stem = _conv_bn(in_c, 384, 1)
        self.b3_a = _conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_conv_bn(in_c, 448, 1),
                                      _conv_bn(448, 384, 3, padding=1))
        self.b3d_a = _conv_bn(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _conv_bn(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _conv_bn(in_c, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return _cat([self.b1(x), self.b3_a(s), self.b3_b(s),
                     self.b3d_a(d), self.b3d_b(d), self.bp(x)])


class InceptionV3(nn.Layer):
    """Reference inceptionv3.py InceptionV3."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _conv_bn(3, 32, 3, stride=2), _conv_bn(32, 32, 3),
            _conv_bn(32, 64, 3, padding=1), nn.MaxPool2D(3, 2),
            _conv_bn(64, 80, 1), _conv_bn(80, 192, 3), nn.MaxPool2D(3, 2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64), _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.reshape([x.shape[0], -1])))
        return x


def inception_v3(pretrained=False, **kw):
    return InceptionV3(**kw)
