"""paddle.vision.models — the model zoo (reference:
python/paddle/vision/models/__init__.py)."""
from .resnet import (BasicBlock, BottleneckBlock, ResNet, resnet18,
                     resnet34, resnet50, resnet101, resnet152)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .mobilenet import (MobileNetV1, MobileNetV2, MobileNetV3Small,
                        MobileNetV3Large, mobilenet_v1, mobilenet_v2,
                        mobilenet_v3_small, mobilenet_v3_large)
from .small_nets import (LeNet, AlexNet, alexnet, SqueezeNet, squeezenet1_0,
                         squeezenet1_1, ShuffleNetV2, shufflenet_v2_x0_25,
                         shufflenet_v2_x0_33, shufflenet_v2_x0_5,
                         shufflenet_v2_x1_0, shufflenet_v2_x1_5,
                         shufflenet_v2_x2_0, shufflenet_v2_swish)
from .densenet import (DenseNet, densenet121, densenet161, densenet169,
                       densenet201, densenet264)
from .inception import GoogLeNet, googlenet, InceptionV3, inception_v3

__all__ = [
    "ResNet", "BasicBlock", "BottleneckBlock", "resnet18", "resnet34",
    "resnet50", "resnet101", "resnet152",
    "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "MobileNetV1", "MobileNetV2", "MobileNetV3Small", "MobileNetV3Large",
    "mobilenet_v1", "mobilenet_v2", "mobilenet_v3_small",
    "mobilenet_v3_large",
    "LeNet", "AlexNet", "alexnet", "SqueezeNet", "squeezenet1_0",
    "squeezenet1_1", "ShuffleNetV2", "shufflenet_v2_x0_25",
    "shufflenet_v2_x0_33", "shufflenet_v2_x0_5", "shufflenet_v2_x1_0",
    "shufflenet_v2_x1_5", "shufflenet_v2_x2_0", "shufflenet_v2_swish",
    "DenseNet", "densenet121", "densenet161", "densenet169", "densenet201",
    "densenet264", "GoogLeNet", "googlenet", "InceptionV3", "inception_v3",
]
