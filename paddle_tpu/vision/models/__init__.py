from .resnet import (BasicBlock, BottleneckBlock, ResNet, resnet18,
                     resnet34, resnet50, resnet101, resnet152)

__all__ = ["ResNet", "BasicBlock", "BottleneckBlock", "resnet18",
           "resnet34", "resnet50", "resnet101", "resnet152"]
