"""DenseNet family (reference: python/paddle/vision/models/densenet.py —
DenseNet with densenet121/161/169/201/264). Dense blocks are concat chains;
XLA fuses the BN-ReLU-conv prologue per layer."""
from __future__ import annotations

from ... import nn


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        import paddle_tpu as paddle
        return paddle.concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.relu = nn.ReLU()
        self.pool = nn.AvgPool2D(2, 2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


_CFGS = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        num_init, growth, block_cfg = _CFGS[layers]
        feats = [nn.Conv2D(3, num_init, 7, stride=2, padding=3,
                           bias_attr=False),
                 nn.BatchNorm2D(num_init), nn.ReLU(),
                 nn.MaxPool2D(3, 2, padding=1)]
        ch = num_init
        for bi, n_layers in enumerate(block_cfg):
            for _ in range(n_layers):
                feats.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if bi != len(block_cfg) - 1:
                feats.append(_Transition(ch, ch // 2))
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.reshape([x.shape[0], -1]))
        return x


def densenet121(pretrained=False, **kw):
    return DenseNet(121, **kw)


def densenet161(pretrained=False, **kw):
    return DenseNet(161, **kw)


def densenet169(pretrained=False, **kw):
    return DenseNet(169, **kw)


def densenet201(pretrained=False, **kw):
    return DenseNet(201, **kw)


def densenet264(pretrained=False, **kw):
    return DenseNet(264, **kw)
