"""AlexNet, SqueezeNet, LeNet, ShuffleNetV2 (reference:
python/paddle/vision/models/{alexnet,squeezenet,lenet,shufflenetv2}.py)."""
from __future__ import annotations

from ... import nn
from ...nn import functional as F


class LeNet(nn.Layer):
    """Reference lenet.py LeNet (the vision-zoo variant)."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120), nn.Linear(120, 84),
                nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape([x.shape[0], -1]))
        return x


class AlexNet(nn.Layer):
    """Reference alexnet.py AlexNet."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
                nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        if self.num_classes > 0:
            x = self.classifier(x.reshape([x.shape[0], -1]))
        return x


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze_c, e1_c, e3_c):
        super().__init__()
        self.squeeze = nn.Conv2D(in_c, squeeze_c, 1)
        self.expand1 = nn.Conv2D(squeeze_c, e1_c, 1)
        self.expand3 = nn.Conv2D(squeeze_c, e3_c, 3, padding=1)
        self.relu = nn.ReLU()

    def forward(self, x):
        x = self.relu(self.squeeze(x))
        import paddle_tpu as paddle
        return paddle.concat([self.relu(self.expand1(x)),
                              self.relu(self.expand3(x))], axis=1)


class SqueezeNet(nn.Layer):
    """Reference squeezenet.py SqueezeNet (versions 1.0 / 1.1)."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                nn.MaxPool2D(3, 2, ceil_mode=True),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier(x)
        if self.with_pool:
            x = self.pool(x)
        return x.reshape([x.shape[0], -1])


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act=nn.ReLU):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act(),
                nn.Conv2D(branch_c, branch_c, 3, stride=1, padding=1,
                          groups=branch_c, bias_attr=False),
                nn.BatchNorm2D(branch_c),
                nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act())
        else:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_c, in_c, 3, stride=stride, padding=1,
                          groups=in_c, bias_attr=False),
                nn.BatchNorm2D(in_c),
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act())
            self.branch2 = nn.Sequential(
                nn.Conv2D(in_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act(),
                nn.Conv2D(branch_c, branch_c, 3, stride=stride, padding=1,
                          groups=branch_c, bias_attr=False),
                nn.BatchNorm2D(branch_c),
                nn.Conv2D(branch_c, branch_c, 1, bias_attr=False),
                nn.BatchNorm2D(branch_c), act())

    def forward(self, x):
        import paddle_tpu as paddle
        if self.stride == 1:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return F.channel_shuffle(out, 2)


_SHUFFLE_CFG = {
    "x0_25": [24, 48, 96, 192, 512], "x0_33": [24, 32, 64, 128, 512],
    "x0_5": [24, 48, 96, 192, 1024], "x1_0": [24, 116, 232, 464, 1024],
    "x1_5": [24, 176, 352, 704, 1024], "x2_0": [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(nn.Layer):
    """Reference shufflenetv2.py ShuffleNetV2."""

    def __init__(self, scale="x1_0", act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        chans = _SHUFFLE_CFG[scale]
        self.conv1 = nn.Sequential(
            nn.Conv2D(3, chans[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(chans[0]), act_layer())
        self.maxpool = nn.MaxPool2D(3, 2, padding=1)
        stages = []
        in_c = chans[0]
        for stage_i, repeat in enumerate((4, 8, 4)):
            out_c = chans[stage_i + 1]
            units = [_ShuffleUnit(in_c, out_c, 2, act_layer)]
            for _ in range(repeat - 1):
                units.append(_ShuffleUnit(out_c, out_c, 1, act_layer))
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_c, chans[-1], 1, bias_attr=False),
            nn.BatchNorm2D(chans[-1]), act_layer())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chans[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape([x.shape[0], -1]))
        return x


def _shuffle(scale, act="relu", **kwargs):
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return _shuffle("x0_25", **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return _shuffle("x0_33", **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return _shuffle("x0_5", **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return _shuffle("x1_0", **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return _shuffle("x1_5", **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return _shuffle("x2_0", **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    return _shuffle("x1_0", act="swish", **kw)
