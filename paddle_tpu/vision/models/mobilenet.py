"""MobileNet v1/v2/v3 (reference: python/paddle/vision/models/
mobilenetv1.py, mobilenetv2.py, mobilenetv3.py). Depthwise convs lower to
grouped XLA convs; hard-swish/hard-sigmoid are VPU-fused elementwise."""
from __future__ import annotations

from ... import nn


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel=3, stride=1, groups=1,
                 act=nn.ReLU):
        pad = (kernel - 1) // 2
        layers = [nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=pad,
                            groups=groups, bias_attr=False),
                  nn.BatchNorm2D(out_c)]
        if act is not None:
            layers.append(act())
        super().__init__(*layers)


# ---------------- v1 ----------------
class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.dw = ConvBNReLU(in_c, in_c, 3, stride, groups=in_c)
        self.pw = ConvBNReLU(in_c, out_c, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    """Reference mobilenetv1.py MobileNetV1."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: int(c * scale)
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + [
                  (512, 1024, 2), (1024, 1024, 1)]
        layers = [ConvBNReLU(3, s(32), 3, 2)]
        for in_c, out_c, stride in cfg:
            layers.append(DepthwiseSeparable(s(in_c), s(out_c), stride))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape([x.shape[0], -1]))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


# ---------------- v2 ----------------
class InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNReLU(in_c, hidden, 1, act=nn.ReLU6))
        layers += [
            ConvBNReLU(hidden, hidden, 3, stride, groups=hidden,
                       act=nn.ReLU6),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """Reference mobilenetv2.py MobileNetV2."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        layers = [ConvBNReLU(3, in_c, 3, 2, act=nn.ReLU6)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                layers.append(InvertedResidual(in_c, out_c,
                                               s if i == 0 else 1, t))
                in_c = out_c
        layers.append(ConvBNReLU(in_c, last_c, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.reshape([x.shape[0], -1]))
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


# ---------------- v3 ----------------
class SqueezeExcite(nn.Layer):
    def __init__(self, ch, squeeze=4):
        super().__init__()
        mid = _make_divisible(ch // squeeze)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, mid, 1)
        self.fc2 = nn.Conv2D(mid, ch, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.pool(x)
        s = self.relu(self.fc1(s))
        s = self.hsig(self.fc2(s))
        return x * s


class V3Block(nn.Layer):
    def __init__(self, in_c, mid_c, out_c, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if mid_c != in_c:
            layers.append(ConvBNReLU(in_c, mid_c, 1, act=act))
        layers.append(ConvBNReLU(mid_c, mid_c, kernel, stride,
                                 groups=mid_c, act=act))
        if use_se:
            layers.append(SqueezeExcite(mid_c))
        layers += [nn.Conv2D(mid_c, out_c, 1, bias_attr=False),
                   nn.BatchNorm2D(out_c)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


_V3_LARGE = [
    # k, exp, out, se, act, stride
    (3, 16, 16, False, nn.ReLU, 1), (3, 64, 24, False, nn.ReLU, 2),
    (3, 72, 24, False, nn.ReLU, 1), (5, 72, 40, True, nn.ReLU, 2),
    (5, 120, 40, True, nn.ReLU, 1), (5, 120, 40, True, nn.ReLU, 1),
    (3, 240, 80, False, nn.Hardswish, 2), (3, 200, 80, False, nn.Hardswish, 1),
    (3, 184, 80, False, nn.Hardswish, 1), (3, 184, 80, False, nn.Hardswish, 1),
    (3, 480, 112, True, nn.Hardswish, 1), (3, 672, 112, True, nn.Hardswish, 1),
    (5, 672, 160, True, nn.Hardswish, 2), (5, 960, 160, True, nn.Hardswish, 1),
    (5, 960, 160, True, nn.Hardswish, 1),
]
_V3_SMALL = [
    (3, 16, 16, True, nn.ReLU, 2), (3, 72, 24, False, nn.ReLU, 2),
    (3, 88, 24, False, nn.ReLU, 1), (5, 96, 40, True, nn.Hardswish, 2),
    (5, 240, 40, True, nn.Hardswish, 1), (5, 240, 40, True, nn.Hardswish, 1),
    (5, 120, 48, True, nn.Hardswish, 1), (5, 144, 48, True, nn.Hardswish, 1),
    (5, 288, 96, True, nn.Hardswish, 2), (5, 576, 96, True, nn.Hardswish, 1),
    (5, 576, 96, True, nn.Hardswish, 1),
]


class MobileNetV3(nn.Layer):
    """Reference mobilenetv3.py MobileNetV3Small/Large."""

    def __init__(self, cfg, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        layers = [ConvBNReLU(3, in_c, 3, 2, act=nn.Hardswish)]
        for k, exp, out, se, act, s in cfg:
            mid = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            layers.append(V3Block(in_c, mid, out_c, k, s, se, act))
            in_c = out_c
        last_exp = _make_divisible(cfg[-1][1] * scale)
        layers.append(ConvBNReLU(in_c, last_exp, 1, act=nn.Hardswish))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_exp, last_c), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.reshape([x.shape[0], -1]))
        return x


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 1280, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)
