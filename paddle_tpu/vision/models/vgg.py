"""VGG family (reference: python/paddle/vision/models/vgg.py — VGG class,
vgg11/13/16/19 with optional batch_norm). Plain conv stacks: all FLOPs land
on the MXU via XLA conv lowering."""
from __future__ import annotations

from ... import nn

_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def _make_layers(cfg, batch_norm=False):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(kernel_size=2, stride=2))
        else:
            layers.append(nn.Conv2D(in_c, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_c = v
    return nn.Sequential(*layers)


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.reshape([x.shape[0], -1])
            x = self.classifier(x)
        return x


def _vgg(cfg, batch_norm, **kwargs):
    return VGG(_make_layers(_CFGS[cfg], batch_norm), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("A", batch_norm, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("B", batch_norm, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("D", batch_norm, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("E", batch_norm, **kwargs)
