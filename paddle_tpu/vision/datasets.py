"""paddle.vision.datasets — MNIST/FashionMNIST/Cifar/ImageFolder.

Reference: python/paddle/vision/datasets/{mnist,cifar,folder}.py. The
reference downloads archives on demand; this environment has no egress, so
constructors take a local ``image_path``/``data_file`` and raise a clear
error when files are absent. Parsing (IDX / cifar pickle) matches the
reference formats byte-for-byte, so files fetched for the reference work
unchanged.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder"]


class MNIST(Dataset):
    """IDX-format MNIST (reference mnist.py MNIST).

    mode: train|test; image_path/label_path point at the (optionally
    .gz-compressed) ubyte files.
    """

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if image_path is None or label_path is None:
            base = os.environ.get(
                "PADDLE_TPU_DATA_HOME",
                os.path.expanduser("~/.cache/paddle_tpu/datasets"))
            tag = "train" if mode == "train" else "t10k"
            image_path = image_path or os.path.join(
                base, self.NAME, f"{tag}-images-idx3-ubyte.gz")
            label_path = label_path or os.path.join(
                base, self.NAME, f"{tag}-labels-idx1-ubyte.gz")
        for p in (image_path, label_path):
            if not os.path.exists(p):
                raise FileNotFoundError(
                    f"{self.NAME}: {p} not found. No-egress environment — "
                    f"place the IDX files there (same files the reference "
                    f"downloads) or pass image_path/label_path.")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(
            path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad IDX image magic {magic}")
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad IDX label magic {magic}")
            return np.frombuffer(f.read(n), dtype=np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        lbl = int(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(lbl, dtype=np.int64)

    def __len__(self):
        return self.images.shape[0]


class FashionMNIST(MNIST):
    """Same IDX layout, different archive (reference mnist.py FashionMNIST)."""

    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR-10 python-pickle archive (reference cifar.py Cifar10)."""

    _batches_train = [f"data_batch_{i}" for i in range(1, 6)]
    _batches_test = ["test_batch"]
    _prefix = "cifar-10-batches-py"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        if data_file is None:
            base = os.environ.get(
                "PADDLE_TPU_DATA_HOME",
                os.path.expanduser("~/.cache/paddle_tpu/datasets"))
            data_file = os.path.join(base, "cifar-10-python.tar.gz")
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"cifar: {data_file} not found (no-egress environment; "
                f"provide the same tar.gz the reference downloads)")
        names = (self._batches_train if mode == "train"
                 else self._batches_test)
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for name in names:
                member = tf.getmember(f"{self._prefix}/{name}")
                with tf.extractfile(member) as f:
                    batch = pickle.load(f, encoding="bytes")
                images.append(np.asarray(batch[b"data"], dtype=np.uint8))
                key = b"labels" if b"labels" in batch else b"fine_labels"
                labels.extend(batch[key])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].transpose(1, 2, 0)  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return self.images.shape[0]


class Cifar100(Cifar10):
    _batches_train = ["train"]
    _batches_test = ["test"]
    _prefix = "cifar-100-python"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if data_file is None:
            base = os.environ.get(
                "PADDLE_TPU_DATA_HOME",
                os.path.expanduser("~/.cache/paddle_tpu/datasets"))
            data_file = os.path.join(base, "cifar-100-python.tar.gz")
        super().__init__(data_file, mode, transform, download, backend)


_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")


class DatasetFolder(Dataset):
    """class-per-subdirectory image tree (reference folder.py DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._pil_loader
        extensions = extensions or _IMG_EXTS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = (is_valid_file(path) if is_valid_file
                          else fname.lower().endswith(tuple(extensions)))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(f"no images found under {root}")

    @staticmethod
    def _pil_loader(path):
        from PIL import Image
        with open(path, "rb") as f:
            return Image.open(f).convert("RGB")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(target, dtype=np.int64)

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat/recursive image list without labels (reference folder.py
    ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._pil_loader
        extensions = extensions or _IMG_EXTS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = (is_valid_file(path) if is_valid_file
                      else fname.lower().endswith(tuple(extensions)))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise ValueError(f"no images found under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)
