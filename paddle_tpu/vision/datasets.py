"""paddle.vision.datasets — MNIST/FashionMNIST/Cifar/ImageFolder.

Reference: python/paddle/vision/datasets/{mnist,cifar,folder}.py. The
reference downloads archives on demand; this environment has no egress, so
constructors take a local ``image_path``/``data_file`` and raise a clear
error when files are absent. Parsing (IDX / cifar pickle) matches the
reference formats byte-for-byte, so files fetched for the reference work
unchanged.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder", "Flowers", "VOC2012"]


class MNIST(Dataset):
    """IDX-format MNIST (reference mnist.py MNIST).

    mode: train|test; image_path/label_path point at the (optionally
    .gz-compressed) ubyte files.
    """

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if image_path is None or label_path is None:
            base = os.environ.get(
                "PADDLE_TPU_DATA_HOME",
                os.path.expanduser("~/.cache/paddle_tpu/datasets"))
            tag = "train" if mode == "train" else "t10k"
            image_path = image_path or os.path.join(
                base, self.NAME, f"{tag}-images-idx3-ubyte.gz")
            label_path = label_path or os.path.join(
                base, self.NAME, f"{tag}-labels-idx1-ubyte.gz")
        for p in (image_path, label_path):
            if not os.path.exists(p):
                raise FileNotFoundError(
                    f"{self.NAME}: {p} not found. No-egress environment — "
                    f"place the IDX files there (same files the reference "
                    f"downloads) or pass image_path/label_path.")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(
            path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad IDX image magic {magic}")
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad IDX label magic {magic}")
            return np.frombuffer(f.read(n), dtype=np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        lbl = int(self.labels[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(lbl, dtype=np.int64)

    def __len__(self):
        return self.images.shape[0]


class FashionMNIST(MNIST):
    """Same IDX layout, different archive (reference mnist.py FashionMNIST)."""

    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR-10 python-pickle archive (reference cifar.py Cifar10)."""

    _batches_train = [f"data_batch_{i}" for i in range(1, 6)]
    _batches_test = ["test_batch"]
    _prefix = "cifar-10-batches-py"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        if data_file is None:
            base = os.environ.get(
                "PADDLE_TPU_DATA_HOME",
                os.path.expanduser("~/.cache/paddle_tpu/datasets"))
            data_file = os.path.join(base, "cifar-10-python.tar.gz")
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"cifar: {data_file} not found (no-egress environment; "
                f"provide the same tar.gz the reference downloads)")
        names = (self._batches_train if mode == "train"
                 else self._batches_test)
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for name in names:
                member = tf.getmember(f"{self._prefix}/{name}")
                with tf.extractfile(member) as f:
                    batch = pickle.load(f, encoding="bytes")
                images.append(np.asarray(batch[b"data"], dtype=np.uint8))
                key = b"labels" if b"labels" in batch else b"fine_labels"
                labels.extend(batch[key])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].transpose(1, 2, 0)  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return self.images.shape[0]


class Cifar100(Cifar10):
    _batches_train = ["train"]
    _batches_test = ["test"]
    _prefix = "cifar-100-python"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if data_file is None:
            base = os.environ.get(
                "PADDLE_TPU_DATA_HOME",
                os.path.expanduser("~/.cache/paddle_tpu/datasets"))
            data_file = os.path.join(base, "cifar-100-python.tar.gz")
        super().__init__(data_file, mode, transform, download, backend)


_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".webp")


class DatasetFolder(Dataset):
    """class-per-subdirectory image tree (reference folder.py DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._pil_loader
        extensions = extensions or _IMG_EXTS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = (is_valid_file(path) if is_valid_file
                          else fname.lower().endswith(tuple(extensions)))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(f"no images found under {root}")

    @staticmethod
    def _pil_loader(path):
        from PIL import Image
        with open(path, "rb") as f:
            return Image.open(f).convert("RGB")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(target, dtype=np.int64)

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat/recursive image list without labels (reference folder.py
    ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._pil_loader
        extensions = extensions or _IMG_EXTS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = (is_valid_file(path) if is_valid_file
                      else fname.lower().endswith(tuple(extensions)))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise ValueError(f"no images found under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Flowers102 (reference flowers.py Flowers): 102flowers.tgz image
    archive + imagelabels.mat + setid.mat subset indices. Like the
    reference, the tgz is extracted to a sibling directory once — gzip
    tars have no cheap random access, and per-file reads are
    fork-worker-safe."""

    _SPLIT_KEY = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        if mode not in self._SPLIT_KEY:
            raise ValueError(f"mode must be train|valid|test, got {mode!r}")
        for p, what in ((data_file, "Flowers images (102flowers.tgz)"),
                        (label_file, "Flowers labels (imagelabels.mat)"),
                        (setid_file, "Flowers splits (setid.mat)")):
            if p is None or not os.path.exists(p):
                raise FileNotFoundError(
                    f"{what}: {p!r} not found (no-egress environment; "
                    f"provide the reference archives)")
        from scipy.io import loadmat
        self.transform = transform
        self.labels = loadmat(label_file)["labels"].ravel()
        self.indexes = loadmat(setid_file)[
            self._SPLIT_KEY[mode]].ravel()
        # reference behavior: one-time extractall next to the archive
        self.data_path = data_file + ".extracted"
        marker = os.path.join(self.data_path, ".complete")
        if not os.path.exists(marker):
            os.makedirs(self.data_path, exist_ok=True)
            with tarfile.open(data_file) as tar:
                tar.extractall(self.data_path)
            open(marker, "w").close()

    def __getitem__(self, idx):
        from PIL import Image
        index = int(self.indexes[idx])
        label = np.array([int(self.labels[index - 1])], np.int64)
        path = os.path.join(self.data_path, "jpg",
                            "image_%05d.jpg" % index)
        image = Image.open(path).convert("RGB")
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """VOC2012 segmentation (reference voc2012.py VOC2012): reads
    JPEGImages + SegmentationClass pairs for the split listed under
    ImageSets/Segmentation/{mode}.txt, straight from the tar."""

    SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
    # reference MODE_FLAG_MAP (voc2012.py): the VOCtrainval archive has no
    # held-out test listing, so 'train' reads trainval and 'test' train
    MODE_FLAG_MAP = {"train": "trainval", "test": "train", "valid": "val"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        if mode not in self.MODE_FLAG_MAP:
            raise ValueError(
                f"mode must be train|valid|test, got {mode!r}")
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                f"VOC2012: {data_file!r} not found (no-egress environment; "
                f"provide the reference VOCtrainval archive)")
        self.transform = transform
        self._data_file = data_file
        self._pid = os.getpid()
        self._tar = tarfile.open(data_file)
        self.name2mem = {m.name: m for m in self._tar.getmembers()}
        listing = self._tar.extractfile(self.name2mem[
            self.SET_FILE.format(self.MODE_FLAG_MAP[mode])]).read().decode()
        self.ids = [l.strip() for l in listing.splitlines() if l.strip()]

    def _tarfile(self):
        # forked DataLoader workers share the parent's fd offset; each
        # process must own its handle
        if os.getpid() != self._pid:
            self._tar = tarfile.open(self._data_file)
            self.name2mem = {m.name: m for m in self._tar.getmembers()}
            self._pid = os.getpid()
        return self._tar

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image
        name = self.ids[idx]
        tar = self._tarfile()
        data = tar.extractfile(
            self.name2mem[self.DATA_FILE.format(name)]).read()
        label = tar.extractfile(
            self.name2mem[self.LABEL_FILE.format(name)]).read()
        image = Image.open(_io.BytesIO(data)).convert("RGB")
        seg = Image.open(_io.BytesIO(label))
        if self.transform is not None:
            image = self.transform(image)
        return image, np.asarray(seg, dtype=np.int64)

    def __len__(self):
        return len(self.ids)
