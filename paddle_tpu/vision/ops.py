"""Detection & vision ops: NMS family, ROI pooling family, anchors, boxes,
YOLO decode/loss, deformable conv, image IO.

Reference: python/paddle/vision/ops.py (nms:1558, roi_align:1198,
roi_pool:1100, psroi_pool:1006, prior_box yolo_box yolo_loss
deform_conv2d:550, distribute_fpn_proposals, generate_proposals:1702,
matrix_nms:376, read_file/decode_jpeg:936) and the phi kernels under
paddle/phi/kernels/gpu/ (nms_kernel.cu, roi_align_kernel.cu,
deformable_conv_kernel.cu, yolo_loss_kernel.cu ...).

TPU-native design notes:
- Greedy NMS is inherently sequential; we run it as a ``lax.scan`` over the
  score-sorted IoU matrix (O(N) steps of O(N) vector work on the VPU) rather
  than the reference's CUDA bitmask kernel. Static shapes in, boolean keep
  mask out; index extraction happens eagerly.
- ROI ops and deform_conv2d are bilinear gathers + reductions: XLA fuses the
  4-corner gathers and lerps; deform_conv2d ends in one MXU matmul over the
  sampled im2col tensor. All differentiable via jax.vjp through
  ``dispatch.call``.
- Anchor/box codecs are pure elementwise math.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor, as_tensor

__all__ = [
    "nms", "matrix_nms", "multiclass_nms", "roi_align", "roi_pool",
    "psroi_pool", "prior_box", "box_coder", "box_clip", "bipartite_match",
    "yolo_box", "yolo_loss", "generate_proposals",
    "distribute_fpn_proposals", "deform_conv2d", "read_file", "decode_jpeg",
    "RoIAlign", "RoIPool", "PSRoIPool", "DeformConv2D",
]


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _iou_matrix(boxes):
    """(N,4) xyxy -> (N,N) pairwise IoU (pure jnp, fuses on VPU)."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _nms_keep_mask(boxes, iou_threshold):
    """Greedy NMS on boxes already in priority order -> bool keep mask.

    lax.scan over rows of the IoU matrix: row i is kept iff no
    previously-kept row suppresses it. Reference CUDA bitmask kernel:
    paddle/phi/kernels/gpu/nms_kernel.cu.
    """
    n = boxes.shape[0]
    iou = _iou_matrix(boxes)
    sup = iou > iou_threshold  # (N, N)

    def step(keep, i):
        # suppressed if any kept j < i has IoU > thr
        mask = (jnp.arange(n) < i) & keep
        suppressed = jnp.any(sup[i] & mask)
        keep = keep.at[i].set(~suppressed)
        return keep, None

    keep0 = jnp.zeros((n,), dtype=bool)
    keep, _ = jax.lax.scan(step, keep0, jnp.arange(n))
    return keep


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy hard NMS; returns indices of kept boxes (score-descending).

    Matches the reference contract (python/paddle/vision/ops.py:1558):
    with ``category_idxs`` NMS is batched per category (boxes offset so
    categories never suppress each other).
    """
    boxes = _t(boxes)
    b = jnp.asarray(boxes._data, dtype=jnp.float32)
    n = b.shape[0]
    if scores is not None:
        s = jnp.asarray(_t(scores)._data, dtype=jnp.float32)
        order = jnp.argsort(-s)
    else:
        order = jnp.arange(n)
    if category_idxs is not None:
        cat = jnp.asarray(_t(category_idxs)._data)
        # offset trick: shift each category into a disjoint coordinate range
        span = jnp.max(b) - jnp.min(b) + 1
        off = (cat.astype(b.dtype) * span)[:, None]
        b = b + off
    sorted_boxes = b[order]
    keep_sorted = _nms_keep_mask(sorted_boxes, iou_threshold)
    # the keep mask is computed on-device; extracting the kept indices is
    # the op's host boundary by contract (variable-length output)
    kept = order[np.asarray(keep_sorted)]  # tpulint: disable=TPU104 — variable-length keep-index extraction is host-by-design
    if top_k is not None:
        kept = kept[:top_k]
    return as_tensor(jnp.asarray(np.asarray(kept)))  # tpulint: disable=TPU104 — materializing the variable-length result


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix (parallel soft) NMS — SOLOv2 style decayed scores.

    Fully parallel (one IoU matrix + max-reductions), which is the
    TPU-friendly NMS. Reference: python/paddle/vision/ops.py:376,
    paddle/phi/kernels/impl/matrix_nms_kernel_impl.h.
    Returns (out[N,6]=[label,score,x1,y1,x2,y2], rois_num, index?).
    """
    bb = jnp.asarray(_t(bboxes)._data, dtype=jnp.float32)
    sc = jnp.asarray(_t(scores)._data, dtype=jnp.float32)
    if bb.ndim == 2:
        bb, sc = bb[None], sc[None]
    outs, nums, idxs = [], [], []
    for bi in range(bb.shape[0]):
        boxes_i, scores_i = bb[bi], sc[bi]
        per_det = []
        for c in range(scores_i.shape[0]):
            if c == background_label:
                continue
            s = scores_i[c]
            # per-class candidate selection: the surviving-box count is
            # data-dependent, so assembly is host-by-design (the decay
            # math itself runs on-device below)
            valid = np.asarray(s > score_threshold)  # tpulint: disable=TPU104 — variable-length candidate extraction is host-by-design
            if not valid.any():  # tpulint: disable=TPU105 — empty-class early-out on host-resident mask
                continue
            vidx = np.nonzero(valid)[0]  # tpulint: disable=TPU104 — variable-length candidate extraction is host-by-design
            s_v, b_v = s[vidx], boxes_i[vidx]
            order = np.asarray(jnp.argsort(-s_v))[:nms_top_k]  # tpulint: disable=TPU104 — variable-length candidate ordering is host-by-design
            s_o, b_o = s_v[order], b_v[order]
            iou = _iou_matrix(b_o)
            n = iou.shape[0]
            tri = jnp.tril(jnp.ones((n, n)), -1)
            iou_max_row = jnp.max(iou * tri, axis=1)  # max IoU w/ higher-score
            # decay_i = min_{j<i} f(iou_ij) / f(iou_max_j), where iou_max_j
            # is competitor j's own max overlap with higher-scored boxes
            if use_gaussian:
                decay = jnp.exp(-(iou * tri) ** 2 / gaussian_sigma)
                comp = jnp.exp(-(iou_max_row[None, :] * tri) ** 2
                               / gaussian_sigma)
            else:
                decay = 1 - iou * tri
                comp = 1 - iou_max_row[None, :] * tri
            decay = jnp.where(tri > 0, decay / jnp.maximum(comp, 1e-10), 1.0)
            dec = jnp.min(decay, axis=1)
            new_s = np.asarray(s_o * dec)  # tpulint: disable=TPU104 — ONE device->host transfer of the decayed scores; detection assembly below is pure numpy
            b_np = np.asarray(b_o)  # tpulint: disable=TPU104 — same single-transfer boundary
            for k in range(n):
                if new_s[k] > post_threshold:  # tpulint: disable=TPU105 — post-threshold filter over the host-resident scores
                    per_det.append((c, float(new_s[k]), b_np[k],  # tpulint: disable=TPU103 — host-resident numpy by this point
                                    int(vidx[order[k]])))  # tpulint: disable=TPU103 — host-resident numpy by this point
        per_det.sort(key=lambda r: -r[1])
        per_det = per_det[:keep_top_k]
        if per_det:
            out = np.stack([np.concatenate([[c], [sv], bx])
                            for c, sv, bx, _ in per_det])
            idx = np.array([i for *_, i in per_det], dtype=np.int32)
        else:
            out = np.zeros((0, 6), dtype=np.float32)
            idx = np.zeros((0,), dtype=np.int64)
        outs.append(out)
        nums.append(len(per_det))
        idxs.append(idx)
    out = as_tensor(jnp.asarray(np.concatenate(outs, axis=0),
                                dtype=jnp.float32))
    rois_num = as_tensor(jnp.asarray(nums, dtype=jnp.int32))
    index = as_tensor(jnp.asarray(np.concatenate(idxs).astype(np.int32)))
    res = [out]
    if return_index:
        res.append(index)
    if return_rois_num:
        res.append(rois_num)
    return tuple(res) if len(res) > 1 else out


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=400,
                   keep_top_k=200, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=-1, return_index=False,
                   return_rois_num=True, name=None):
    """Per-class hard NMS then global top-k (reference multiclass_nms3 op,
    paddle/phi/kernels/impl/multiclass_nms3_kernel_impl.h via
    python/paddle/vision/ops.py multiclass_nms)."""
    bb = jnp.asarray(_t(bboxes)._data, dtype=jnp.float32)
    sc = jnp.asarray(_t(scores)._data, dtype=jnp.float32)
    if bb.ndim == 2:
        bb, sc = bb[None], sc[None]
    outs, nums, idxs = [], [], []
    for bi in range(bb.shape[0]):
        boxes_i, scores_i = bb[bi], sc[bi]
        dets = []
        for c in range(scores_i.shape[0]):
            if c == background_label:
                continue
            s = scores_i[c]
            # per-class NMS emits a data-dependent number of detections:
            # candidate extraction + final assembly are host-by-design,
            # while the keep mask itself comes from the on-device scan
            valid = np.nonzero(np.asarray(s > score_threshold))[0]  # tpulint: disable=TPU104 — variable-length candidate extraction is host-by-design
            if valid.size == 0:
                continue
            s_v, b_v = s[valid], boxes_i[valid]
            order = np.asarray(jnp.argsort(-s_v))[:nms_top_k]  # tpulint: disable=TPU104 — variable-length candidate ordering is host-by-design
            keep = _nms_keep_mask(b_v[order], nms_threshold)
            for k in np.nonzero(np.asarray(keep))[0]:  # tpulint: disable=TPU104 — variable-length keep-index extraction is host-by-design
                gi = int(valid[order[k]])  # tpulint: disable=TPU103 — host-resident numpy index by this point
                dets.append((c, float(s_v[order[k]]), np.asarray(b_v[order[k]]),  # tpulint: disable=TPU103,TPU104 — assembling the variable-length host output
                             gi))
        dets.sort(key=lambda r: -r[1])
        dets = dets[:keep_top_k]
        if dets:
            out = np.stack([np.concatenate([[c], [sv], bx])
                            for c, sv, bx, _ in dets])
            idx = np.array([bi * boxes_i.shape[0] + i for *_, i in dets],
                           dtype=np.int64)
        else:
            out = np.zeros((0, 6), dtype=np.float32)
            idx = np.zeros((0,), dtype=np.int64)
        outs.append(out)
        nums.append(len(dets))
        idxs.append(idx)
    out = as_tensor(jnp.asarray(np.concatenate(outs, axis=0)))
    res = [out]
    if return_index:
        res.append(as_tensor(jnp.asarray(np.concatenate(idxs))))
    if return_rois_num:
        res.append(as_tensor(jnp.asarray(nums, dtype=jnp.int32)))
    return tuple(res) if len(res) > 1 else out


def _roi_batch_index(boxes_num, n_rois):
    """Expand per-image ROI counts into a per-ROI batch index vector."""
    bn = np.asarray(boxes_num, dtype=np.int64)
    return jnp.asarray(np.repeat(np.arange(bn.shape[0]), bn), dtype=jnp.int32)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (Mask R-CNN): average of bilinear samples per output bin.

    Differentiable in x and boxes. Reference:
    python/paddle/vision/ops.py:1198, phi/kernels/gpu/roi_align_kernel.cu.
    """
    x, boxes = _t(x), _t(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    if boxes.shape[0] == 0:
        return as_tensor(jnp.zeros((0, x.shape[1], ph, pw),
                                   dtype=x._data.dtype))
    batch_idx = _roi_batch_index(
        boxes_num.numpy() if hasattr(boxes_num, "numpy") else boxes_num,
        boxes.shape[0])

    def f(a, rois):
        n, c, h, w = a.shape
        offset = 0.5 if aligned else 0.0
        x1 = rois[:, 0] * spatial_scale - offset
        y1 = rois[:, 1] * spatial_scale - offset
        x2 = rois[:, 2] * spatial_scale - offset
        y2 = rois[:, 3] * spatial_scale - offset
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        if sampling_ratio > 0:
            sr_h = sr_w = None  # fixed grid
            cap_h = cap_w = sampling_ratio
        else:
            # reference adaptivity (roi_align_kernel: ceil(roi/out) samples
            # per bin, per ROI). Counts are data (fine under jit); only the
            # static CAP needs a concrete value — take it from the boxes
            # when eager, else fall back to 4 samples.
            sr_h = jnp.maximum(jnp.ceil(bin_h), 1.0)
            sr_w = jnp.maximum(jnp.ceil(bin_w), 1.0)
            if isinstance(rois, jax.core.Tracer):
                cap_h = cap_w = 4
                sr_h = jnp.minimum(sr_h, cap_h)
                sr_w = jnp.minimum(sr_w, cap_w)
            else:
                # eager-only: pick the static sampling cap from the real
                # boxes (one scalar read); traced callers take the fixed
                # cap=4 branch above, so no sync ever happens under jit
                cap_h = max(int(jnp.max(sr_h)), 1)  # tpulint: disable=TPU103 — eager-only static-cap selection, unreachable under tracing
                cap_w = max(int(jnp.max(sr_w)), 1)  # tpulint: disable=TPU103 — eager-only static-cap selection, unreachable under tracing
        # sample grid: (R, ph, cap) y-coords x (R, pw, cap) x-coords; with
        # adaptive counts, sample k of bin (k+0.5)/sr_i and mask k >= sr_i
        if sr_h is None:
            off_h = (jnp.arange(cap_h)[None, None, :] + 0.5) / cap_h
            off_w = (jnp.arange(cap_w)[None, None, :] + 0.5) / cap_w
            wgt_h = jnp.ones((rois.shape[0], 1, cap_h))
            wgt_w = jnp.ones((rois.shape[0], 1, cap_w))
            cnt = float(cap_h * cap_w)
        else:
            kh = jnp.arange(cap_h)[None, None, :]
            kw = jnp.arange(cap_w)[None, None, :]
            off_h = (kh + 0.5) / sr_h[:, None, None]
            off_w = (kw + 0.5) / sr_w[:, None, None]
            wgt_h = (kh < sr_h[:, None, None]).astype(jnp.float32)
            wgt_w = (kw < sr_w[:, None, None]).astype(jnp.float32)
            cnt = None
        iy = (y1[:, None, None] + bin_h[:, None, None]
              * (jnp.arange(ph)[None, :, None] + off_h))
        ix = (x1[:, None, None] + bin_w[:, None, None]
              * (jnp.arange(pw)[None, :, None] + off_w))

        def bilinear(img, yy, xx):
            # img (c,h,w); yy (ph,sr); xx (pw,sr) -> (c, ph, sr, pw, sr)
            y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
            y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
            wy1 = jnp.clip(yy, 0, h - 1) - y0
            wx1 = jnp.clip(xx, 0, w - 1) - x0
            wy0, wx0 = 1 - wy1, 1 - wx1
            # outside image -> 0 contribution (reference clamps then zeros)
            oky = (yy >= -1) & (yy <= h)
            okx = (xx >= -1) & (xx <= w)

            def g(yi, xi):
                return img[:, yi][:, :, :, xi]  # (c, ph, sr, pw, sr)

            v = (g(y0i, x0i) * (wy0[:, :, None, None] * wx0[None, None])
                 + g(y0i, x1i) * (wy0[:, :, None, None] * wx1[None, None])
                 + g(y1i, x0i) * (wy1[:, :, None, None] * wx0[None, None])
                 + g(y1i, x1i) * (wy1[:, :, None, None] * wx1[None, None]))
            ok = oky[:, :, None, None] & okx[None, None]
            return v * ok.astype(v.dtype)

        def per_roi(r):
            img = a[batch_idx[r]]
            v = bilinear(img, iy[r], ix[r])      # (c, ph, cap_h, pw, cap_w)
            w_ = (wgt_h[r][0][None, None, :, None, None]
                  * wgt_w[r][0][None, None, None, None, :])
            denom = cnt if cnt is not None else (sr_h[r] * sr_w[r])
            return (v * w_).sum(axis=(2, 4)) / denom   # (c, ph, pw)

        return jax.vmap(per_roi)(jnp.arange(rois.shape[0]))

    return dispatch.call("roi_align", f, [x, boxes])


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool (Fast R-CNN): max over integer bins.

    Masked-max formulation: for each bin, max over pixels whose index falls
    inside the bin — O(P^2·H·W) vector work, static shapes, no dynamic
    slicing. Reference: python/paddle/vision/ops.py:1100,
    phi/kernels/gpu/roi_pool_kernel.cu.
    """
    x, boxes = _t(x), _t(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    batch_idx = _roi_batch_index(
        boxes_num.numpy() if hasattr(boxes_num, "numpy") else boxes_num,
        boxes.shape[0])

    def f(a, rois):
        n, c, h, w = a.shape
        x1 = jnp.round(rois[:, 0] * spatial_scale)
        y1 = jnp.round(rois[:, 1] * spatial_scale)
        x2 = jnp.round(rois[:, 2] * spatial_scale)
        y2 = jnp.round(rois[:, 3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)

        def per_roi(r):
            img = a[batch_idx[r]]  # (c,h,w)
            hs = jnp.clip(jnp.floor(y1[r] + jnp.arange(ph) * bin_h[r]), 0, h)
            he = jnp.clip(jnp.ceil(y1[r] + (jnp.arange(ph) + 1) * bin_h[r]),
                          0, h)
            ws_ = jnp.clip(jnp.floor(x1[r] + jnp.arange(pw) * bin_w[r]), 0, w)
            we = jnp.clip(jnp.ceil(x1[r] + (jnp.arange(pw) + 1) * bin_w[r]),
                          0, w)
            my = (ys[None, :] >= hs[:, None]) & (ys[None, :] < he[:, None])
            mx = (xs[None, :] >= ws_[:, None]) & (xs[None, :] < we[:, None])
            m = my[:, None, :, None] & mx[None, :, None, :]  # (ph,pw,h,w)
            neg = jnp.finfo(a.dtype).min
            v = jnp.where(m[None], img[:, None, None], neg)
            out = v.max(axis=(-2, -1))  # (c, ph, pw)
            empty = ~m.any(axis=(-2, -1))
            return jnp.where(empty[None], 0.0, out)

        return jax.vmap(per_roi)(jnp.arange(rois.shape[0]))

    return dispatch.call("roi_pool", f, [x, boxes])


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pool (R-FCN).

    Input channels C = out_c * ph * pw; output bin (i,j) averages channel
    group (k, i, j). Reference: python/paddle/vision/ops.py:1006,
    phi/kernels/gpu/psroi_pool_kernel.cu.
    """
    x, boxes = _t(x), _t(boxes)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    batch_idx = _roi_batch_index(
        boxes_num.numpy() if hasattr(boxes_num, "numpy") else boxes_num,
        boxes.shape[0])

    def f(a, rois):
        n, c, h, w = a.shape
        out_c = c // (ph * pw)
        # reference psroi kernel rounds in input coords, THEN scales
        x1 = jnp.round(rois[:, 0]) * spatial_scale
        y1 = jnp.round(rois[:, 1]) * spatial_scale
        x2 = (jnp.round(rois[:, 2]) + 1) * spatial_scale
        y2 = (jnp.round(rois[:, 3]) + 1) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h, bin_w = rh / ph, rw / pw
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)

        def per_roi(r):
            img = a[batch_idx[r]].reshape(out_c, ph, pw, h, w)
            hs = jnp.clip(jnp.floor(y1[r] + jnp.arange(ph) * bin_h[r]), 0, h)
            he = jnp.clip(jnp.ceil(y1[r] + (jnp.arange(ph) + 1) * bin_h[r]),
                          0, h)
            ws_ = jnp.clip(jnp.floor(x1[r] + jnp.arange(pw) * bin_w[r]), 0, w)
            we = jnp.clip(jnp.ceil(x1[r] + (jnp.arange(pw) + 1) * bin_w[r]),
                          0, w)
            my = (ys[None, :] >= hs[:, None]) & (ys[None, :] < he[:, None])
            mx = (xs[None, :] >= ws_[:, None]) & (xs[None, :] < we[:, None])
            m = (my[:, None, :, None] & mx[None, :, None, :]).astype(a.dtype)
            s = jnp.einsum("kijhw,ijhw->kij", img, m)
            cnt = m.sum(axis=(-2, -1))
            return jnp.where(cnt[None] > 0, s / jnp.maximum(cnt[None], 1), 0.0)

        return jax.vmap(per_roi)(jnp.arange(rois.shape[0]))

    return dispatch.call("psroi_pool", f, [x, boxes])


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD anchor generation (reference python/paddle/vision/ops.py prior_box,
    phi/kernels/impl/prior_box_kernel_impl.h). Pure index math."""
    input, image = _t(input), _t(image)
    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_h = steps[1] if steps[1] > 0 else ih / fh
    step_w = steps[0] if steps[0] > 0 else iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    boxes = []
    for s in min_sizes:
        sizes = []
        if min_max_aspect_ratios_order:
            sizes.append((s, s))
            if max_sizes:
                mx = max_sizes[min_sizes.index(s)]
                sizes.append((np.sqrt(s * mx), np.sqrt(s * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                sizes.append((s * np.sqrt(ar), s / np.sqrt(ar)))
        else:
            for ar in ars:
                sizes.append((s * np.sqrt(ar), s / np.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[min_sizes.index(s)]
                sizes.append((np.sqrt(s * mx), np.sqrt(s * mx)))
        boxes.extend(sizes)
    num_priors = len(boxes)
    cx = (np.arange(fw) + offset) * step_w
    cy = (np.arange(fh) + offset) * step_h
    gx, gy = np.meshgrid(cx, cy)  # (fh, fw)
    out = np.zeros((fh, fw, num_priors, 4), dtype=np.float32)
    for k, (bw, bh) in enumerate(boxes):
        out[:, :, k, 0] = (gx - bw / 2) / iw
        out[:, :, k, 1] = (gy - bh / 2) / ih
        out[:, :, k, 2] = (gx + bw / 2) / iw
        out[:, :, k, 3] = (gy + bh / 2) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, dtype=np.float32),
                          out.shape).copy()
    return as_tensor(jnp.asarray(out)), as_tensor(jnp.asarray(var))


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode gt boxes to deltas / decode deltas to boxes (R-CNN codec).

    Reference: python/paddle/vision/ops.py box_coder,
    phi/kernels/impl/box_coder.h.
    """
    pb = jnp.asarray(_t(prior_box)._data, dtype=jnp.float32)
    tb = jnp.asarray(_t(target_box)._data, dtype=jnp.float32)
    pbv = None
    if prior_box_var is not None:
        if isinstance(prior_box_var, (list, tuple)):
            pbv = jnp.asarray(prior_box_var, dtype=jnp.float32)
        else:
            pbv = jnp.asarray(_t(prior_box_var)._data, dtype=jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph_ = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph_ / 2
    if code_type == "encode_center_size":
        # tb (M,4) gt; output (M, N, 4) deltas for each prior
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw / 2
        tcy = tb[:, 1] + th / 2
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph_[None, :]
        dw = jnp.log(tw[:, None] / pw[None, :])
        dh = jnp.log(th[:, None] / ph_[None, :])
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        if pbv is not None:
            out = out / (pbv if pbv.ndim == 1 else pbv[None, :, :])
        return as_tensor(out)
    # decode: tb (N, K, 4) deltas (axis selects prior broadcast dim)
    if tb.ndim == 2:
        tb = tb[:, None, :]
    if axis == 0:
        pcx_b, pcy_b = pcx[:, None], pcy[:, None]
        pw_b, ph_b = pw[:, None], ph_[:, None]
        if pbv is not None and pbv.ndim == 2:
            pbv = pbv[:, None, :]
    else:
        pcx_b, pcy_b = pcx[None, :], pcy[None, :]
        pw_b, ph_b = pw[None, :], ph_[None, :]
        if pbv is not None and pbv.ndim == 2:
            pbv = pbv[None, :, :]
    d = tb if pbv is None else tb * pbv
    cx = d[..., 0] * pw_b + pcx_b
    cy = d[..., 1] * ph_b + pcy_b
    w_ = jnp.exp(d[..., 2]) * pw_b
    h_ = jnp.exp(d[..., 3]) * ph_b
    out = jnp.stack([cx - w_ / 2, cy - h_ / 2,
                     cx + w_ / 2 - norm, cy + h_ / 2 - norm], axis=-1)
    return as_tensor(out)


def box_clip(input, im_info, name=None):
    """Clip boxes to image bounds given im_info [h, w, scale].

    Reference: phi/kernels/impl/box_clip_kernel_impl.h."""
    b = _t(input)
    info = jnp.asarray(_t(im_info)._data, dtype=jnp.float32)

    def f(boxes):
        h = info[..., 0] / info[..., 2] - 1
        w = info[..., 1] / info[..., 2] - 1
        x1 = jnp.clip(boxes[..., 0], 0, w)
        y1 = jnp.clip(boxes[..., 1], 0, h)
        x2 = jnp.clip(boxes[..., 2], 0, w)
        y2 = jnp.clip(boxes[..., 3], 0, h)
        return jnp.stack([x1, y1, x2, y2], axis=-1)

    return dispatch.call("box_clip", f, [b])


def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=0.5,
                    name=None):
    """Greedy bipartite matching of rows (gt) to columns (priors).

    Returns (match_indices (1, N_col), match_dist (1, N_col)).
    Reference: phi/kernels/impl/bipartite_match_kernel_impl.h.

    In-graph formulation: min(nr, nc) ``fori_loop`` steps of one global
    argmax + row/col masking — static shapes throughout, so the whole
    match runs on-device (and traces under to_static/SOT) instead of the
    former host loop.
    """
    dist = _t(dist_matrix)

    def f(d):
        nr, nc = d.shape

        def step(_, carry):
            work, midx, mdist = carry
            flat = jnp.argmax(work)
            r = flat // nc
            c = flat % nc
            v = work[r, c]
            take = v > 0
            new_work = work.at[r, :].set(-1.0).at[:, c].set(-1.0)
            return (jnp.where(take, new_work, work),
                    jnp.where(take, midx.at[c].set(r.astype(jnp.int32)),
                              midx),
                    jnp.where(take, mdist.at[c].set(v), mdist))

        midx = jnp.full((nc,), -1, jnp.int32)
        mdist = jnp.zeros((nc,), d.dtype)
        _, midx, mdist = jax.lax.fori_loop(
            0, min(nr, nc), step, (d, midx, mdist))
        if match_type == "per_prediction":
            best_r = jnp.argmax(d, axis=0).astype(jnp.int32)
            best_v = jnp.max(d, axis=0)
            fill = (midx == -1) & (best_v >= dist_threshold)
            midx = jnp.where(fill, best_r, midx)
            mdist = jnp.where(fill, best_v, mdist)
        return midx[None], mdist[None]

    return dispatch.call("bipartite_match", f, [dist])


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5, name=None):
    """Decode YOLOv3 head output into boxes + scores.

    x: (N, A*(5+C), H, W). Returns (boxes (N, A*H*W, 4),
    scores (N, A*H*W, C)). Reference: phi/kernels/gpu/yolo_box_kernel.cu,
    python/paddle/vision/ops.py yolo_box.
    """
    x = _t(x)
    imgs = jnp.asarray(_t(img_size)._data, dtype=jnp.float32)
    anchors = np.asarray(anchors, dtype=np.float32).reshape(-1, 2)
    na = anchors.shape[0]

    def f(a):
        n, _, h, w = a.shape
        if iou_aware:
            # reference layout (phi/kernels/funcs/yolo_box_util.h): the na
            # IoU channels are a LEADING block before the na*(5+C) box block
            ioup = jax.nn.sigmoid(a[:, :na])
            a = a[:, na:].reshape(n, na, -1, h, w)
        else:
            a = a.reshape(n, na, -1, h, w)
        gx = jnp.arange(w, dtype=jnp.float32)
        gy = jnp.arange(h, dtype=jnp.float32)
        bx = ((jax.nn.sigmoid(a[:, :, 0]) - 0.5) * scale_x_y + 0.5
              + gx[None, None, None, :]) / w
        by = ((jax.nn.sigmoid(a[:, :, 1]) - 0.5) * scale_x_y + 0.5
              + gy[None, None, :, None]) / h
        in_w = downsample_ratio * w
        in_h = downsample_ratio * h
        bw = jnp.exp(a[:, :, 2]) * anchors[None, :, 0, None, None] / in_w
        bh = jnp.exp(a[:, :, 3]) * anchors[None, :, 1, None, None] / in_h
        conf = jax.nn.sigmoid(a[:, :, 4])
        if iou_aware:
            conf = conf ** (1 - iou_aware_factor) * ioup ** iou_aware_factor
        cls = jax.nn.sigmoid(a[:, :, 5:])  # (n, na, C, h, w)
        score = conf[:, :, None] * cls
        keep = (conf >= conf_thresh).astype(a.dtype)
        imw = imgs[:, 1][:, None, None, None]
        imh = imgs[:, 0][:, None, None, None]
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=2) * keep[:, :, None]
        scores = score * keep[:, :, None]
        boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(n, -1, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(n, -1, cls.shape[2])
        return boxes, scores

    return dispatch.call("yolo_box", f, [x])


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 training loss (coord + obj + class), per-sample sum.

    Differentiable in x. Best-anchor matching on the host (gt are data),
    losses as fused jnp. Reference: phi/kernels/impl/yolo_loss_kernel_impl.h.
    """
    x = _t(x)
    # Ground-truth target assignment is host-by-design: gt boxes/labels
    # are input DATA (not traced model compute), the assignment scatters
    # a handful of cells per image, and its outputs feed the traced loss
    # as constants — one transfer per batch, amortized over the fused
    # on-device loss math in f() below.
    gtb = np.asarray(_t(gt_box)._data, dtype=np.float32)   # tpulint: disable=TPU104 — host gt target assembly by design (see note above)
    gtl = np.asarray(_t(gt_label)._data)                   # tpulint: disable=TPU104 — host gt target assembly by design
    gts = (np.asarray(_t(gt_score)._data, dtype=np.float32)  # tpulint: disable=TPU104 — host gt target assembly by design
           if gt_score is not None else np.ones(gtl.shape, np.float32))
    anchors_np = np.asarray(anchors, dtype=np.float32).reshape(-1, 2)
    mask = list(anchor_mask)
    na = len(mask)
    n, _, h, w = x.shape
    in_w = downsample_ratio * w
    in_h = downsample_ratio * h

    # --- host-side target assignment (gt data, not traced) ---
    tobj = np.zeros((n, na, h, w), np.float32)
    tscale = np.zeros((n, na, h, w), np.float32)
    ttxy = np.zeros((n, na, 2, h, w), np.float32)
    ttwh = np.zeros((n, na, 2, h, w), np.float32)
    tcls = np.zeros((n, na, class_num, h, w), np.float32)
    gt_xyxy = []  # per-image list of gt boxes in xyxy grid-normalized
    for b in range(n):
        boxes_img = []
        for t in range(gtb.shape[1]):
            cx, cy, bw, bh = gtb[b, t]
            if bw <= 0 or bh <= 0:  # tpulint: disable=TPU105 — host gt target assembly by design
                continue
            boxes_img.append([cx - bw / 2, cy - bh / 2,
                              cx + bw / 2, cy + bh / 2])
            # best anchor over ALL anchors by shape IoU
            inter = (np.minimum(anchors_np[:, 0], bw * in_w)  # tpulint: disable=TPU104 — host gt target assembly by design
                     * np.minimum(anchors_np[:, 1], bh * in_h))  # tpulint: disable=TPU104 — host gt target assembly by design
            union = (anchors_np[:, 0] * anchors_np[:, 1]
                     + bw * in_w * bh * in_h - inter)
            best = int(np.argmax(inter / np.maximum(union, 1e-10)))  # tpulint: disable=TPU103,TPU104 — host gt target assembly by design
            if best not in mask:
                continue
            k = mask.index(best)
            gi = min(int(cx * w), w - 1)  # tpulint: disable=TPU103 — host gt target assembly by design
            gj = min(int(cy * h), h - 1)  # tpulint: disable=TPU103 — host gt target assembly by design
            tobj[b, k, gj, gi] = gts[b, t]
            tscale[b, k, gj, gi] = 2.0 - bw * bh
            ttxy[b, k, 0, gj, gi] = cx * w - gi
            ttxy[b, k, 1, gj, gi] = cy * h - gj
            ttwh[b, k, 0, gj, gi] = np.log(  # tpulint: disable=TPU104 — host gt target assembly by design
                max(bw * in_w / anchors_np[best, 0], 1e-9))
            ttwh[b, k, 1, gj, gi] = np.log(  # tpulint: disable=TPU104 — host gt target assembly by design
                max(bh * in_h / anchors_np[best, 1], 1e-9))
            lbl = int(gtl[b, t])  # tpulint: disable=TPU103 — host gt target assembly by design
            smooth = 1.0 / class_num if use_label_smooth and class_num > 1 else 0.0
            tcls[b, k, :, gj, gi] = smooth
            tcls[b, k, lbl, gj, gi] = 1.0 - smooth if use_label_smooth else 1.0
        gt_xyxy.append(np.asarray(boxes_img, np.float32).reshape(-1, 4))
    maxg = max((g.shape[0] for g in gt_xyxy), default=0)
    gt_pad = np.zeros((n, max(maxg, 1), 4), np.float32)
    gt_valid = np.zeros((n, max(maxg, 1)), np.float32)
    for b, g in enumerate(gt_xyxy):
        gt_pad[b, :g.shape[0]] = g
        gt_valid[b, :g.shape[0]] = 1.0
    masked_anchors = anchors_np[mask]

    def f(a):
        a = a.reshape(n, na, 5 + class_num, h, w)
        px = jax.nn.sigmoid(a[:, :, 0])
        py = jax.nn.sigmoid(a[:, :, 1])
        pw_ = a[:, :, 2]
        ph_ = a[:, :, 3]
        pobj = a[:, :, 4]
        pcls = a[:, :, 5:]
        obj = jnp.asarray(tobj)
        sc = jnp.asarray(tscale) * obj

        def bce(logit_or_p, t, from_logits):
            if from_logits:
                return jnp.maximum(logit_or_p, 0) - logit_or_p * t + jnp.log1p(
                    jnp.exp(-jnp.abs(logit_or_p)))
            p = jnp.clip(logit_or_p, 1e-7, 1 - 1e-7)
            return -(t * jnp.log(p) + (1 - t) * jnp.log(1 - p))

        loss_xy = (bce(px, jnp.asarray(ttxy[:, :, 0]), False)
                   + bce(py, jnp.asarray(ttxy[:, :, 1]), False)) * sc
        loss_wh = (jnp.abs(pw_ - jnp.asarray(ttwh[:, :, 0]))
                   + jnp.abs(ph_ - jnp.asarray(ttwh[:, :, 1]))) * sc
        # ignore mask: predicted boxes with IoU > thresh vs any gt
        gx = (px + jnp.arange(w)[None, None, None, :]) / w
        gy = (py + jnp.arange(h)[None, None, :, None]) / h
        gw = jnp.exp(pw_) * masked_anchors[None, :, 0, None, None] / in_w
        gh = jnp.exp(ph_) * masked_anchors[None, :, 1, None, None] / in_h
        p1x, p1y = gx - gw / 2, gy - gh / 2
        p2x, p2y = gx + gw / 2, gy + gh / 2
        gtp = jnp.asarray(gt_pad)  # (n, G, 4)
        gv = jnp.asarray(gt_valid)
        ix1 = jnp.maximum(p1x[..., None], gtp[:, None, None, None, :, 0])
        iy1 = jnp.maximum(p1y[..., None], gtp[:, None, None, None, :, 1])
        ix2 = jnp.minimum(p2x[..., None], gtp[:, None, None, None, :, 2])
        iy2 = jnp.minimum(p2y[..., None], gtp[:, None, None, None, :, 3])
        inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
        area_p = (gw * gh)[..., None]
        area_g = ((gtp[:, :, 2] - gtp[:, :, 0])
                  * (gtp[:, :, 3] - gtp[:, :, 1]))[:, None, None, None, :]
        iou = inter / jnp.maximum(area_p + area_g - inter, 1e-10)
        best_iou = jnp.max(iou * gv[:, None, None, None, :], axis=-1)
        ignore = (best_iou > ignore_thresh) & (obj == 0)
        obj_mask = jnp.where(ignore, 0.0, 1.0)
        loss_obj = bce(pobj, obj, True) * obj_mask
        loss_cls = (bce(pcls, jnp.asarray(tcls), True)
                    * obj[:, :, None]).sum(axis=2)
        total = (loss_xy + loss_wh + loss_obj + loss_cls)
        return total.sum(axis=(1, 2, 3))

    return dispatch.call("yolo_loss", f, [x])


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=True, name=None):
    """RPN proposal generation: decode anchors+deltas, clip, filter, NMS.

    Reference: python/paddle/vision/ops.py:1702,
    phi/kernels/gpu/generate_proposals_kernel.cu.
    """
    sc = jnp.asarray(_t(scores)._data, jnp.float32)       # (N, A, H, W)
    bd = jnp.asarray(_t(bbox_deltas)._data, jnp.float32)  # (N, 4A, H, W)
    ims = jnp.asarray(_t(img_size)._data, jnp.float32)    # (N, 2) h,w
    anc = jnp.asarray(_t(anchors)._data, jnp.float32).reshape(-1, 4)
    var = jnp.asarray(_t(variances)._data, jnp.float32).reshape(-1, 4)
    n = sc.shape[0]
    offset = 1.0 if pixel_offset else 0.0

    def decode(s_map, d_map, im):
        """All the vector math on-device: score-ordered decode, clip,
        min-size validity — one fused program per image. Only the
        kept-index extraction below crosses to the host (the output is
        variable-length by contract)."""
        s = s_map.transpose(1, 2, 0).reshape(-1)
        d = d_map.reshape(-1, 4, s_map.shape[1], s_map.shape[2])
        d = d.transpose(2, 3, 0, 1).reshape(-1, 4)
        order = jnp.argsort(-s)[:pre_nms_top_n]
        s, d = s[order], d[order]
        a, v = anc[order], var[order]
        aw = a[:, 2] - a[:, 0] + offset
        ah = a[:, 3] - a[:, 1] + offset
        acx = a[:, 0] + aw / 2
        acy = a[:, 1] + ah / 2
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        w_ = jnp.exp(jnp.minimum(v[:, 2] * d[:, 2], 10.0)) * aw
        h_ = jnp.exp(jnp.minimum(v[:, 3] * d[:, 3], 10.0)) * ah
        props = jnp.stack([cx - w_ / 2, cy - h_ / 2,
                           cx + w_ / 2 - offset, cy + h_ / 2 - offset],
                          axis=1)
        imh, imw = im[0], im[1]
        props = jnp.clip(
            props,
            jnp.zeros((4,), jnp.float32),
            jnp.stack([imw - offset, imh - offset,
                       imw - offset, imh - offset]))
        ws = props[:, 2] - props[:, 0] + offset
        hs = props[:, 3] - props[:, 1] + offset
        return props, s, (ws >= min_size) & (hs >= min_size)

    all_rois, all_scores, nums = [], [], []
    for b in range(n):
        props, s, valid = decode(sc[b], bd[b], ims[b])
        # host boundary by design from here: rois are variable-length
        vidx = np.nonzero(np.asarray(valid))[0]  # tpulint: disable=TPU104 — variable-length keep-index extraction is the op's host boundary
        if vidx.shape[0] == 0:
            all_rois.append(np.zeros((0, 4), np.float32))
            all_scores.append(np.zeros((0,), np.float32))
            nums.append(0)
            continue
        props_v = jnp.take(props, vidx, axis=0)
        km = _nms_keep_mask(props_v, nms_thresh)
        kept = vidx[np.nonzero(np.asarray(km))[0][:post_nms_top_n]]  # tpulint: disable=TPU104 — NMS keep indices are data-dependent-shape host output by design
        all_rois.append(np.asarray(jnp.take(props, kept, axis=0)))  # tpulint: disable=TPU104 — materializing the variable-length result
        all_scores.append(np.asarray(jnp.take(s, kept)))  # tpulint: disable=TPU104 — materializing the variable-length result
        nums.append(kept.shape[0])
    rois = as_tensor(jnp.asarray(np.concatenate(all_rois, 0)))
    rscores = as_tensor(jnp.asarray(np.concatenate(all_scores, 0)))
    if return_rois_num:
        return rois, rscores, as_tensor(jnp.asarray(nums, dtype=jnp.int32))
    return rois, rscores


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Assign ROIs to FPN levels by scale (FPN paper eqn. 1).

    Reference: python/paddle/vision/ops.py distribute_fpn_proposals."""
    rois_j = jnp.asarray(_t(fpn_rois)._data, jnp.float32)
    offset = 1.0 if pixel_offset else 0.0
    # level assignment (FPN eqn. 1) runs on-device; only the per-level
    # grouping below crosses to the host (variable-length level buckets
    # are the op's output contract)
    ws = rois_j[:, 2] - rois_j[:, 0] + offset
    hs = rois_j[:, 3] - rois_j[:, 1] + offset
    scale = jnp.sqrt(jnp.maximum(ws * hs, 0))
    lvl_dev = jnp.clip(jnp.floor(jnp.log2(scale / refer_scale + 1e-8))
                       + refer_level, min_level, max_level)
    lvl = np.asarray(lvl_dev).astype(np.int64)  # tpulint: disable=TPU104 — single transfer; per-level bucket extraction is host-by-design
    rois = np.asarray(rois_j)  # tpulint: disable=TPU104 — same single-transfer host boundary
    multi_rois, restore = [], np.zeros(rois.shape[0], dtype=np.int64)
    rois_num_per = []
    pos = 0
    for L in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == L)[0]  # tpulint: disable=TPU104 — per-level bucket extraction over the host-resident lvl array
        multi_rois.append(as_tensor(jnp.asarray(rois[idx])))
        restore[idx] = np.arange(pos, pos + idx.shape[0])
        rois_num_per.append(as_tensor(jnp.asarray([idx.shape[0]],
                                                  dtype=jnp.int32)))
        pos += idx.shape[0]
    restore_t = as_tensor(jnp.asarray(restore[:, None]))
    if rois_num is not None:
        return multi_rois, restore_t, rois_num_per
    return multi_rois, restore_t


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2: bilinear-sample at learned offsets, then one
    MXU matmul over the sampled im2col tensor.

    x (N,Cin,H,W); offset (N, 2*dg*kh*kw, Ho, Wo); mask (N, dg*kh*kw, Ho, Wo)
    for v2. Reference: python/paddle/vision/ops.py:550,
    phi/kernels/impl/deformable_conv_kernel_impl.h.
    """
    x, offset, weight = _t(x), _t(offset), _t(weight)
    tensors = [x, offset, weight]
    if mask is not None:
        mask = _t(mask)
        tensors.append(mask)
    if bias is not None:
        bias = _t(bias)
        tensors.append(bias)
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dil = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def f(a, off, w_, *rest):
        m = rest[0] if mask is not None else None
        bval = (rest[-1] if bias is not None else None)
        n, cin, h, wid = a.shape
        cout, cin_g, kh, kw = w_.shape
        dg = deformable_groups
        ho = (h + 2 * p[0] - (dil[0] * (kh - 1) + 1)) // s[0] + 1
        wo = (wid + 2 * p[1] - (dil[1] * (kw - 1) + 1)) // s[1] + 1
        off = off.reshape(n, dg, kh * kw, 2, ho, wo)
        # base sampling positions (ky, kx, ho, wo)
        base_y = (jnp.arange(ho)[None, :, None] * s[0] - p[0]
                  + jnp.arange(kh)[:, None, None] * dil[0])  # (kh, ho, 1)
        base_y = jnp.broadcast_to(base_y[:, None], (kh, kw, ho, wo))
        bx = (jnp.arange(wo)[None, :] * s[1] - p[1]
              + jnp.arange(kw)[:, None] * dil[1])  # (kw, wo)
        base_x = jnp.broadcast_to(bx[None, :, None, :], (kh, kw, ho, wo))
        base = jnp.stack([base_y, base_x], axis=0).reshape(2, kh * kw, ho, wo)
        # sample positions per batch/dgroup: (n, dg, kk, 2, ho, wo)
        posy = base[0][None, None] + off[:, :, :, 0]
        posx = base[1][None, None] + off[:, :, :, 1]

        cpg = cin // dg  # channels per deformable group

        def sample(img, py, px):
            # img (cin, h, w); py/px (dg, kk, ho, wo) -> (cin, kk, ho, wo)
            y0 = jnp.floor(py)
            x0 = jnp.floor(px)
            wy1 = py - y0
            wx1 = px - x0
            vals = 0.0
            for dy, wy in ((0, 1 - wy1), (1, wy1)):
                for dx, wx in ((0, 1 - wx1), (1, wx1)):
                    yi = (y0 + dy).astype(jnp.int32)
                    xi = (x0 + dx).astype(jnp.int32)
                    ok = ((yi >= 0) & (yi < h) & (xi >= 0) & (xi < wid))
                    yi = jnp.clip(yi, 0, h - 1)
                    xi = jnp.clip(xi, 0, wid - 1)
                    # per-dgroup gather
                    img_g = img.reshape(dg, cpg, h, wid)
                    g = jax.vmap(lambda im, y, x, o:
                                 im[:, y, x] * o.astype(im.dtype))(
                        img_g, yi, xi, ok)  # (dg, cpg, kk, ho, wo)
                    vals = vals + g * (wy * wx)[:, None]
            return vals.reshape(cin, kh * kw, ho, wo)

        cols = jax.vmap(sample)(a, posy, posx)  # (n, cin, kk, ho, wo)
        if m is not None:
            mm = m.reshape(n, dg, kh * kw, ho, wo)
            mm = jnp.repeat(mm, cpg, axis=1).reshape(n, cin, kh * kw, ho, wo)
            cols = cols * mm
        # grouped matmul: w (cout, cin/g, kh*kw)
        wmat = w_.reshape(groups, cout // groups, cin_g * kh * kw)
        cols = cols.reshape(n, groups, cin_g * kh * kw, ho * wo)
        out = jnp.einsum("gok,ngkp->ngop", wmat, cols)
        out = out.reshape(n, cout, ho, wo)
        if bval is not None:
            out = out + bval.reshape(1, -1, 1, 1)
        return out

    return dispatch.call("deform_conv2d", f, tensors)


def read_file(filename, name=None):
    """Read raw bytes of a file into a uint8 tensor (reference
    python/paddle/vision/ops.py:936)."""
    with open(filename, "rb") as fh:
        data = np.frombuffer(fh.read(), dtype=np.uint8)
    return as_tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to (C, H, W) uint8 via PIL (host op — the
    reference uses nvjpeg, phi/kernels/gpu/decode_jpeg_kernel.cu; image IO
    stays on host on TPU)."""
    import io as _io
    from PIL import Image
    raw = bytes(np.asarray(_t(x)._data, dtype=np.uint8))  # tpulint: disable=TPU104 — image decode is a host op by design (PIL; nvjpeg-class decode has no TPU analogue)
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)  # tpulint: disable=TPU104 — PIL image to numpy, still inside the host decode boundary
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return as_tensor(jnp.asarray(arr))


# ---- Layer wrappers ----
from ..nn.layer.layers import Layer  # noqa: E402


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


class DeformConv2D(Layer):
    """Deformable conv layer (reference python/paddle/vision/ops.py
    DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from .. import nn
        kh, kw = ((kernel_size, kernel_size)
                  if isinstance(kernel_size, int) else kernel_size)
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.deformable_groups, self.groups = deformable_groups, groups
        import math
        k = 1.0 / math.sqrt(in_channels * kh * kw)
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, kh, kw),
            default_initializer=nn.initializer.Uniform(-k, k))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_channels,), is_bias=True,
                default_initializer=nn.initializer.Uniform(-k, k))
        else:
            self.bias = None

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self.stride,
                             self.padding, self.dilation,
                             self.deformable_groups, self.groups, mask)


def correlation(x1, x2, pad_size, kernel_size, max_displacement, stride1,
                stride2, corr_type_multiply=1, name=None):
    """FlowNet correlation (cost volume) between two feature maps.

    out[n, k, i, j] = mean over channels and the kernel_size² patch of
    x1[n,c,si+u,sj+v] · x2[n,c,si+di+u,sj+dj+v] for each displacement
    (di,dj) on the stride2 grid within ±max_displacement — one fused
    gather+reduce per static displacement plus a box filter for the
    patch, which XLA vectorizes; no CUDA kernel needed.
    Reference: phi/kernels/gpu/correlation_kernel.cu.
    """
    if kernel_size % 2 != 1:
        raise ValueError("correlation: kernel_size must be odd")
    xt1, xt2 = _t(x1), _t(x2)
    d = max_displacement // stride2
    r = (kernel_size - 1) // 2
    border = max_displacement + r
    if pad_size < border:
        raise ValueError(
            f"correlation: pad_size {pad_size} must cover "
            f"max_displacement + (kernel_size-1)//2 = {border}")

    def f(a, b):
        n, c, h, w = a.shape
        pad_cfg = ((0, 0), (0, 0), (pad_size, pad_size), (pad_size, pad_size))
        ap = jnp.pad(a, pad_cfg)
        bp = jnp.pad(b, pad_cfg)
        hp, wp = h + 2 * pad_size, w + 2 * pad_size
        # reference output covers only positions where every displaced
        # PATCH stays inside the padded map: [border, Hp-border) — sliced
        # reads, never jnp.roll (roll would wrap displaced reads to the
        # far edge)
        eh, ew = hp - 2 * border, wp - 2 * border
        base = ap[:, :, border - r:border + eh + r,
                  border - r:border + ew + r]
        outs = []
        for di in range(-d, d + 1):
            for dj in range(-d, d + 1):
                oy, ox = di * stride2, dj * stride2
                shifted = bp[:, :, border + oy - r:border + oy + eh + r,
                             border + ox - r:border + ox + ew + r]
                prod = (base * shifted).mean(axis=1)  # (n, eh+2r, ew+2r)
                if r:
                    prod = jax.lax.reduce_window(
                        prod, 0.0, jax.lax.add,
                        (1, kernel_size, kernel_size), (1, 1, 1),
                        "VALID") / float(kernel_size * kernel_size)
                outs.append(prod)                     # (n, eh, ew)
        out = jnp.stack(outs, axis=1)
        return out[:, :, ::stride1, ::stride1]

    return dispatch.call("correlation", f, [xt1, xt2])


__all__.append("correlation")
