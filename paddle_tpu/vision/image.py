"""Image backend selection (reference: python/paddle/vision/image.py —
set_image_backend :24 / get_image_backend / image_load with 'pil' and
'cv2' backends; cv2 is optional and gated)."""
from __future__ import annotations

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_image_backend = "pil"


def set_image_backend(backend: str) -> None:
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'tensor'], but "
            f"got {backend}")
    _image_backend = backend


def get_image_backend() -> str:
    return _image_backend


def image_load(path: str, backend: str | None = None):
    """Load an image with the selected backend (reference image_load)."""
    backend = backend or _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(
            f"Expected backend are one of ['pil', 'cv2', 'tensor'], but "
            f"got {backend}")
    if backend == "cv2":
        try:
            import cv2
        except ImportError:
            raise ImportError(
                "backend 'cv2' requires opencv-python, which is not "
                "installed; use the 'pil' backend")
        return cv2.imread(path)
    from PIL import Image
    img = Image.open(path)
    if backend == "tensor":
        import numpy as np

        from .. import to_tensor
        return to_tensor(np.asarray(img))
    return img
