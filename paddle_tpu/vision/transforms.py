"""paddle.vision.transforms — host-side image preprocessing.

Reference: python/paddle/vision/transforms/transforms.py (Compose,
BaseTransform and the transform set) + functional.py. TPU-native stance:
transforms run on HOST numpy/PIL inside DataLoader workers (the native C++
collation path feeds the device); nothing here traces into XLA. Accepts
PIL.Image or numpy HWC arrays, like the reference's cv2/PIL backends.
"""
from __future__ import annotations

import numbers
import random

import numpy as np

__all__ = [
    "Compose", "BaseTransform", "ToTensor", "Resize", "RandomResizedCrop",
    "CenterCrop", "RandomCrop", "RandomHorizontalFlip",
    "RandomVerticalFlip", "Normalize", "Transpose", "Pad", "RandomRotation",
    "Grayscale", "ColorJitter", "BrightnessTransform", "ContrastTransform",
    "SaturationTransform", "HueTransform", "RandomErasing",
    # functional
    "to_tensor", "resize", "crop", "center_crop", "hflip", "vflip",
    "normalize", "pad", "rotate", "to_grayscale", "adjust_brightness",
    "adjust_contrast", "adjust_hue", "erase",
]


def _is_pil(img):
    try:
        from PIL import Image
        return isinstance(img, Image.Image)
    except ImportError:  # pragma: no cover
        return False


def _to_pil(img):
    from PIL import Image
    if _is_pil(img):
        return img
    arr = np.asarray(img)
    if arr.ndim == 3 and arr.shape[2] == 1:
        arr = arr[:, :, 0]  # PIL has no (H, W, 1) mode; use mode L
    if arr.dtype != np.uint8:
        # normalized float input: scale to the uint8 range instead of
        # truncating everything in [0, 1] to {0, 1}
        if np.issubdtype(arr.dtype, np.floating) and arr.size \
                and float(arr.max()) <= 1.0 and float(arr.min()) >= 0.0:
            arr = arr * 255.0
        arr = np.clip(arr, 0, 255).astype(np.uint8)
    return Image.fromarray(arr)


def _resample_float(arr, op):
    """Apply a PIL geometric op to a float HWC array channel-wise via
     32-bit 'F' mode images (lossless for float inputs)."""
    from PIL import Image
    chans = [np.asarray(op(Image.fromarray(arr[:, :, c].astype(np.float32),
                                           mode="F")))
             for c in range(arr.shape[2])]
    return np.stack(chans, axis=-1).astype(arr.dtype)


def _to_np(img):
    """HWC uint8/float numpy view of a PIL image or array."""
    if _is_pil(img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


# ------------------------------ functional ------------------------------
def to_tensor(pic, data_format="CHW"):
    """PIL/HWC-uint8 -> float32 [0,1] Tensor (reference functional.to_tensor)."""
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    raw = _to_np(pic)
    arr = raw.astype(np.float32)
    if raw.dtype == np.uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


def resize(img, size, interpolation="bilinear"):
    from PIL import Image
    modes = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
             "bicubic": Image.BICUBIC, "lanczos": Image.LANCZOS}
    if _is_pil(img):
        w0, h0 = img.size  # free attribute — no pixel decode
        arr0 = None
    else:
        arr0 = _to_np(img)
        h0, w0 = arr0.shape[:2]
    if isinstance(size, int):
        if w0 < h0:
            ow, oh = size, int(size * h0 / w0)
        else:
            ow, oh = int(size * w0 / h0), size
    else:
        oh, ow = size
    if arr0 is not None and np.issubdtype(arr0.dtype, np.floating):
        return _resample_float(
            arr0, lambda im: im.resize((ow, oh), modes[interpolation]))
    out = _to_pil(img).resize((ow, oh), modes[interpolation])
    return out if _is_pil(img) else _to_np(out)


def crop(img, top, left, height, width):
    if _is_pil(img):
        return img.crop((left, top, left + width, top + height))
    return _to_np(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = _to_np(img)
    h, w = arr.shape[:2]
    th, tw = output_size
    top = int(round((h - th) / 2.0))
    left = int(round((w - tw) / 2.0))
    return crop(img, top, left, th, tw)


def hflip(img):
    if _is_pil(img):
        from PIL import Image
        return img.transpose(Image.FLIP_LEFT_RIGHT)
    return _to_np(img)[:, ::-1]


def vflip(img):
    if _is_pil(img):
        from PIL import Image
        return img.transpose(Image.FLIP_TOP_BOTTOM)
    return _to_np(img)[::-1]


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    return (arr - mean) / std


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_np(img)
    if isinstance(padding, numbers.Number):
        padding = (padding,) * 4  # left, top, right, bottom
    elif len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    l, t, r, b = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kwargs = {"constant_values": fill} if mode == "constant" else {}
    out = np.pad(arr, ((t, b), (l, r), (0, 0)), mode=mode, **kwargs)
    return _to_pil(out) if _is_pil(img) else out


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    from PIL import Image
    modes = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
             "bicubic": Image.BICUBIC}
    arr0 = None if _is_pil(img) else _to_np(img)
    if arr0 is not None and np.issubdtype(arr0.dtype, np.floating):
        def chan_fill(c):
            if isinstance(fill, (tuple, list)):
                return float(fill[c] if c < len(fill) else fill[-1])
            return float(fill)
        from PIL import Image
        chans = [np.asarray(Image.fromarray(
            arr0[:, :, c].astype(np.float32), mode="F").rotate(
                angle, resample=modes[interpolation], expand=expand,
                center=center, fillcolor=chan_fill(c)))
            for c in range(arr0.shape[2])]
        return np.stack(chans, axis=-1).astype(arr0.dtype)
    out = _to_pil(img).rotate(angle, resample=modes[interpolation],
                              expand=expand, center=center, fillcolor=fill)
    return out if _is_pil(img) else _to_np(out)


def to_grayscale(img, num_output_channels=1):
    pil = _to_pil(img).convert("L")
    if num_output_channels == 3:
        arr = np.asarray(pil)
        out = np.stack([arr] * 3, axis=-1)
        return _to_pil(out) if _is_pil(img) else out
    return pil if _is_pil(img) else _to_np(pil)


def adjust_brightness(img, factor):
    raw = _to_np(img)
    arr = raw.astype(np.float32) * factor
    if raw.dtype == np.uint8:
        out = np.clip(arr, 0, 255).astype(np.uint8)
    else:
        out = arr.astype(raw.dtype)  # float pipeline: dtype-preserving
    return _to_pil(out) if _is_pil(img) else out


def adjust_contrast(img, factor):
    raw = _to_np(img)
    arr = raw.astype(np.float32)
    mean = arr.mean()
    out = (arr - mean) * factor + mean
    if raw.dtype == np.uint8:
        out = np.clip(out, 0, 255).astype(np.uint8)
    else:
        out = out.astype(raw.dtype)
    return _to_pil(out) if _is_pil(img) else out


def adjust_hue(img, factor):
    if not _is_pil(img) and np.issubdtype(np.asarray(img).dtype,
                                          np.floating):
        raise TypeError(
            "adjust_hue requires a uint8/PIL image (HSV path); apply it "
            "before ToTensor/Normalize in the pipeline")
    pil = _to_pil(img).convert("HSV")
    h, s, v = pil.split()
    h_arr = np.asarray(h, dtype=np.int16)
    h_arr = ((h_arr + int(factor * 255)) % 256).astype(np.uint8)
    from PIL import Image
    out = Image.merge("HSV", (Image.fromarray(h_arr), s, v)).convert("RGB")
    return out if _is_pil(img) else _to_np(out)


def erase(img, i, j, h, w, v, inplace=False):
    from ..core.tensor import Tensor
    if isinstance(img, Tensor):
        import jax.numpy as jnp
        arr = img._data
        arr = arr.at[..., i:i + h, j:j + w].set(jnp.asarray(v))
        return Tensor(arr)
    arr = _to_np(img).copy()
    arr[i:i + h, j:j + w] = v
    return arr


# ------------------------------- classes --------------------------------
class BaseTransform:
    """Reference transforms.BaseTransform: callable with _apply_image."""

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):  # pragma: no cover - abstract
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = pad(img, self.padding, self.fill, self.padding_mode)
        arr = _to_np(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = pad(img, (max(tw - w, 0), max(th - h, 0)), self.fill,
                      self.padding_mode)
            arr = _to_np(img)
            h, w = arr.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return crop(img, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _to_np(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                return resize(crop(img, top, left, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if random.random() < self.prob else img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        from ..core.tensor import Tensor
        if isinstance(img, Tensor):
            import jax.numpy as jnp
            m = jnp.asarray(self.mean, dtype=img._data.dtype)
            s = jnp.asarray(self.std, dtype=img._data.dtype)
            if self.data_format == "CHW":
                m = m.reshape(-1, 1, 1)
                s = s.reshape(-1, 1, 1)
            return Tensor((img._data - m) / s)
        return normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return _to_np(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding, self.fill, self.mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, self.expand,
                      self.center, self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        if not _is_pil(img) and np.issubdtype(np.asarray(img).dtype,
                                              np.floating):
            raise TypeError(
                "SaturationTransform requires a uint8/PIL image; apply it "
                "before ToTensor/Normalize in the pipeline")
        from PIL import ImageEnhance
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        out = ImageEnhance.Color(_to_pil(img)).enhance(factor)
        return out if _is_pil(img) else _to_np(out)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(-self.value, self.value)
        return adjust_hue(img, factor)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.transforms[i](img)
        return img


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob, self.scale, self.ratio, self.value = (prob, scale, ratio,
                                                         value)

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        from ..core.tensor import Tensor
        if isinstance(img, Tensor):
            h, w = img.shape[-2], img.shape[-1]
        else:
            arr = _to_np(img)
            h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target / ar)))
            ew = int(round(np.sqrt(target * ar)))
            if eh < h and ew < w:
                top = random.randint(0, h - eh)
                left = random.randint(0, w - ew)
                return erase(img, top, left, eh, ew, self.value)
        return img
