"""paddle.vision surface (reference: python/paddle/vision/)."""
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401

__all__ = ["models", "ops", "transforms", "datasets"]
