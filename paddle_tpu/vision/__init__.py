"""paddle.vision surface (reference: python/paddle/vision/)."""
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from .image import get_image_backend, image_load, set_image_backend

__all__ = ["models", "ops", "transforms", "datasets",
           "set_image_backend", "get_image_backend", "image_load"]
