"""paddle.audio.functional — windows + mel filterbanks.

Reference: python/paddle/audio/functional/window.py get_window,
functional.py hz_to_mel/mel_to_hz/compute_fbank_matrix. Pure jnp math.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..core.tensor import Tensor


def get_window(window: str, win_length: int, fftbins: bool = True,
               dtype: str = "float32") -> Tensor:
    n = win_length
    periodic = fftbins
    m = n if periodic else n - 1
    k = jnp.arange(n)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * jnp.cos(2 * math.pi * k / m)
    elif window == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * math.pi * k / m)
    elif window == "blackman":
        w = (0.42 - 0.5 * jnp.cos(2 * math.pi * k / m)
             + 0.08 * jnp.cos(4 * math.pi * k / m))
    elif window in ("rect", "boxcar", "ones"):
        w = jnp.ones((n,))
    else:
        raise ValueError(f"unsupported window {window!r}")
    return Tensor(w.astype(dtype))


def hz_to_mel(freq, htk: bool = False):
    if htk:
        return 2595.0 * math.log10(1.0 + freq / 700.0)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (freq - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if freq >= min_log_hz:
        mels = min_log_mel + math.log(freq / min_log_hz) / logstep
    return mels


def mel_to_hz(mel, htk: bool = False):
    if htk:
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if mel >= min_log_mel:
        freqs = min_log_hz * math.exp(logstep * (mel - min_log_mel))
    return freqs


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max=None,
                         htk: bool = False, dtype: str = "float32"
                         ) -> Tensor:
    """[n_mels, n_fft//2 + 1] triangular mel filterbank."""
    f_max = f_max or sr / 2.0
    n_bins = n_fft // 2 + 1
    fft_freqs = jnp.linspace(0, sr / 2.0, n_bins)
    mel_lo, mel_hi = hz_to_mel(f_min, htk), hz_to_mel(f_max, htk)
    mel_pts = [mel_to_hz(mel_lo + (mel_hi - mel_lo) * i / (n_mels + 1),
                         htk) for i in range(n_mels + 2)]
    mel_pts = jnp.asarray(mel_pts)
    lower = mel_pts[:-2][:, None]
    center = mel_pts[1:-1][:, None]
    upper = mel_pts[2:][:, None]
    up = (fft_freqs[None, :] - lower) / jnp.maximum(center - lower, 1e-10)
    down = (upper - fft_freqs[None, :]) / jnp.maximum(upper - center,
                                                      1e-10)
    fbank = jnp.maximum(0.0, jnp.minimum(up, down))
    return Tensor(fbank.astype(dtype))


__all__ = ["get_window", "hz_to_mel", "mel_to_hz", "compute_fbank_matrix"]
