"""paddle.audio (reference: python/paddle/audio/ — features, functional,
backends)."""
from . import backends, features, functional
from .backends import info, load, save

__all__ = ["features", "functional", "backends", "info", "load", "save"]
