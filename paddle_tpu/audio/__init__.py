from . import features, functional

__all__ = ["features", "functional"]
