"""Audio IO backend (reference: python/paddle/audio/backends/
wave_backend.py — info/load/save over the stdlib wave module, the
fallback backend when soundfile is absent; backend registry in
backends/init_backend.py). Host-side IO, like the reference.
"""
from __future__ import annotations

import wave
from dataclasses import dataclass

import numpy as np

__all__ = ["AudioInfo", "info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend"]


@dataclass
class AudioInfo:
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str = "PCM_S"


def info(filepath: str) -> AudioInfo:
    """reference wave_backend.py:37."""
    with wave.open(filepath, "rb") as f:
        return AudioInfo(sample_rate=f.getframerate(),
                         num_samples=f.getnframes(),
                         num_channels=f.getnchannels(),
                         bits_per_sample=8 * f.getsampwidth())


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """Read a PCM wav -> (Tensor [C, L] (or [L, C]), sample_rate)
    (reference wave_backend.py:89). normalize=True scales to [-1, 1]
    float32 like the reference."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    with wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(min(frame_offset, f.getnframes()))
        n = f.getnframes() - f.tell() if num_frames < 0 else num_frames
        raw = f.readframes(n)
    if width == 2:
        data = np.frombuffer(raw, dtype="<i2")
        scale = 32768.0
    elif width == 1:  # unsigned 8-bit PCM
        data = np.frombuffer(raw, dtype=np.uint8)
        scale = 128.0
    elif width == 4:
        data = np.frombuffer(raw, dtype="<i4")
        scale = 2147483648.0
    else:
        raise ValueError(f"unsupported sample width {width}")
    data = data.reshape(-1, nch)
    if normalize:
        data = data.astype(np.float32)
        if width == 1:
            data = data - 128.0
        data = data / scale
    # normalize=False: raw integer PCM, reference wave_backend contract
    if channels_first:
        data = data.T
    return Tensor(jnp.asarray(np.ascontiguousarray(data))), sr


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_16", bits_per_sample: int = 16):
    """Write float [-1,1] (or int16) data as PCM wav (reference
    wave_backend.py:168)."""
    if bits_per_sample != 16 or encoding != "PCM_16":
        raise NotImplementedError("save supports PCM_16 only")
    arr = np.asarray(getattr(src, "numpy", lambda: src)())
    if arr.ndim == 1:
        arr = arr[None] if channels_first else arr[:, None]
    if channels_first:
        arr = arr.T  # -> (L, C)
    if np.issubdtype(arr.dtype, np.floating):
        arr = np.clip(arr, -1.0, 1.0)
        arr = (arr * 32767.0).astype("<i2")
    elif arr.dtype == np.int16:
        arr = arr.astype("<i2")
    elif arr.dtype == np.int32:
        arr = (arr >> 16).astype("<i2")  # rescale 32-bit PCM
    elif arr.dtype == np.uint8:
        arr = ((arr.astype(np.int16) - 128) << 8).astype("<i2")
    else:
        raise ValueError(
            f"save: unsupported integer dtype {arr.dtype}; pass float "
            f"[-1,1] or int16/int32/uint8 PCM")
    with wave.open(filepath, "wb") as f:
        f.setnchannels(arr.shape[1])
        f.setsampwidth(2)
        f.setframerate(sample_rate)
        f.writeframes(arr.tobytes())


_backend = "wave"


def list_available_backends():
    return ["wave"]


def get_current_backend():
    return _backend


def set_backend(backend_name: str):
    global _backend
    if backend_name not in list_available_backends():
        raise ValueError(f"unknown audio backend {backend_name!r}")
    _backend = backend_name
