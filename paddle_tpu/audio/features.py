"""paddle.audio.features — Spectrogram / MelSpectrogram / LogMelSpectrogram
/ MFCC layers (reference: python/paddle/audio/features/layers.py). Built on
paddle.signal.stft + audio.functional filterbanks; every stage is a
dispatched jnp op so features are differentiable (trainable front ends).
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from . import functional as AF


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length=None, win_length=None,
                 window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 dtype: str = "float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer(
            "window", AF.get_window(window, self.win_length, dtype=dtype))

    def forward(self, x):
        from .. import signal
        spec = signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                           window=self.window, center=self.center,
                           pad_mode=self.pad_mode)

        def f(s):
            mag = jnp.abs(s)
            return mag if self.power == 1.0 else mag ** self.power
        return dispatch.call("spectrogram_power", f, [spec])


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length=None,
                 win_length=None, window: str = "hann", power: float = 2.0,
                 center: bool = True, pad_mode: str = "reflect",
                 n_mels: int = 64, f_min: float = 50.0, f_max=None,
                 htk: bool = False, dtype: str = "float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode,
                                       dtype)
        self.register_buffer(
            "fbank", AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min,
                                             f_max, htk, dtype))

    def forward(self, x):
        spec = self.spectrogram(x)        # [..., n_bins, frames]

        def f(s, fb):
            return jnp.einsum("mf,...ft->...mt", fb, s)
        return dispatch.call("mel_project", f, [spec, self.fbank])


class LogMelSpectrogram(Layer):
    def __init__(self, *args, ref_value: float = 1.0, amin: float = 1e-10,
                 top_db=None, **kwargs):
        super().__init__()
        self.mel = MelSpectrogram(*args, **kwargs)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        m = self.mel(x)

        def f(s):
            log_spec = 10.0 * jnp.log10(jnp.maximum(s, self.amin))
            log_spec = log_spec - 10.0 * math.log10(
                max(self.ref_value, self.amin))
            if self.top_db is not None:
                log_spec = jnp.maximum(log_spec,
                                       log_spec.max() - self.top_db)
            return log_spec
        return dispatch.call("log_mel", f, [m])


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 13, **mel_kwargs):
        super().__init__()
        self.log_mel = LogMelSpectrogram(sr=sr, **mel_kwargs)
        self.n_mfcc = n_mfcc

    def forward(self, x):
        logm = self.log_mel(x)

        def f(s):
            # DCT-II over the mel axis (orthonormal)
            n = s.shape[-2]
            k = jnp.arange(n)[None, :]
            m = jnp.arange(self.n_mfcc)[:, None]
            basis = jnp.cos(math.pi * m * (2 * k + 1) / (2 * n))
            scale = jnp.where(m == 0, math.sqrt(1.0 / n),
                              math.sqrt(2.0 / n))
            return jnp.einsum("cm,...mt->...ct", basis * scale, s)
        return dispatch.call("mfcc_dct", f, [logm])


__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]
