"""paddle.quantization — PTQ/QAT surface.

Capability parity with the reference quantization stack (reference:
python/paddle/quantization/ — QuantConfig config.py, PTQ ptq.py, QAT
qat.py; weight_quantize/weight_dequantize ops in phi). TPU-native: int8
abs-max weight quantization as jnp ops (the VPU handles int8<->fp convert;
XLA fuses dequant into the consuming matmul), fake-quant QAT via a
straight-through estimator expressed with stop_gradient — no custom CUDA
kernels needed.
"""
from __future__ import annotations

import copy
from typing import Optional

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor, as_tensor
from ..nn.layer.layers import Layer


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def weight_quantize(x, algo: str = "abs_max", bits: int = 8):
    """-> (int8 weights, per-channel (last dim) fp scales) (reference op
    weight_quantize)."""
    if algo not in ("abs_max", "weight_only_int8"):
        raise NotImplementedError(f"algo {algo!r}")
    qmax = 2 ** (bits - 1) - 1

    def f(w):
        scale = jnp.max(jnp.abs(w), axis=0, keepdims=True) / qmax
        scale = jnp.maximum(scale, 1e-8)
        q = jnp.clip(jnp.round(w / scale), -qmax - 1, qmax)
        return q.astype(jnp.int8), scale[0]
    out = dispatch.call("weight_quantize", f, [_t(x)])
    return out[0], out[1]


def weight_dequantize(q, scale):
    """Dequantize int8/int4 weights back to float using per-channel scales
    (reference weight_dequantize)."""
    def f(qa, s):
        return qa.astype(s.dtype) * s[None, :]
    return dispatch.call("weight_dequantize", f, [_t(q), _t(scale)])


def fake_quant(x, scale=None, bits: int = 8):
    """QAT fake-quant with straight-through estimator (reference
    fake_quantize_dequantize ops): forward rounds, backward passes
    through."""
    qmax = 2 ** (bits - 1) - 1

    def f(a):
        s = (jnp.max(jnp.abs(a)) / qmax) if scale is None else scale
        s = jnp.maximum(s, 1e-8)
        q = jnp.clip(jnp.round(a / s), -qmax - 1, qmax) * s
        # STE: q = a + stop_grad(q - a) -> dq/da = 1
        return a + jax.lax.stop_gradient(q - a)
    return dispatch.call("fake_quantize_dequantize", f, [_t(x)])


class QuantConfig:
    """reference quantization/config.py QuantConfig."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer_types = []

    def add_type_config(self, layer_type, activation=None, weight=None):
        self._layer_types.append((layer_type, activation, weight))
        return self


class QuantedLinear(Layer):
    """Linear running on int8 weights + fp scales (weight-only PTQ)."""

    def __init__(self, linear):
        super().__init__()
        q, scale = weight_quantize(linear.weight)
        # detached inference constants: no tape lineage back to the fp
        # weight, no VJP recording on serving forwards
        self.qweight = Tensor(q._data)
        self.scales = Tensor(scale._data)
        self.bias = getattr(linear, "bias", None)

    def forward(self, x):
        def f(a, q, s, *b):
            w = q.astype(a.dtype) * s[None, :]
            out = a @ w
            if b:
                out = out + b[0]
            return out
        args = [x if isinstance(x, Tensor) else as_tensor(x),
                self.qweight, self.scales]
        if self.bias is not None:
            args.append(self.bias)
        return dispatch.call("quant_linear", f, args)


class PTQ:
    """Post-training weight-only quantization driver (reference ptq.py):
    swap eligible Linear layers for QuantedLinear."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        from ..nn import Linear
        target = model if inplace else copy.deepcopy(model)
        if isinstance(target, Linear):      # bare top-level Linear
            return QuantedLinear(target)
        for name, layer in list(target.named_sublayers()):
            if isinstance(layer, Linear):
                owner = target._locate_owner(name)
                attr = name.rsplit(".", 1)[-1]
                if owner is not None:
                    owner.add_sublayer(attr, QuantedLinear(layer))
        return target


class QAT:
    """Quantization-aware training driver (reference qat.py): Linear
    forwards compute with fake-quantized weights; the STE passes gradients
    through to the fp master weights the optimizer holds."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        from ..nn import Linear
        from ..nn import functional as F
        target = model if inplace else copy.deepcopy(model)
        layers = [target] if isinstance(target, Linear) else []
        layers += [l for _, l in target.named_sublayers()]
        for layer in layers:
            if isinstance(layer, Linear) and not getattr(
                    layer, "_qat_wrapped", False):
                def qat_forward(x, _layer=layer):
                    return F.linear(x, fake_quant(_layer.weight),
                                    getattr(_layer, "bias", None))
                layer.forward = qat_forward
                layer._qat_wrapped = True
        return target


__all__ = ["weight_quantize", "weight_dequantize", "fake_quant",
           "QuantConfig", "QuantedLinear", "PTQ", "QAT"]


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """Matmul against int8/int4-quantized weights with on-the-fly dequant
    (reference weight_only_linear op, phi/kernels/gpu/
    weight_only_linear_kernel.cu). TPU-native: dequantize into the matmul —
    XLA fuses the scale multiply into the MXU epilogue; activations stay in
    their original dtype.

    weight: (in, out) int8, weight_scale: (out,).
    """
    if weight_dtype != "int8":
        raise NotImplementedError(
            f"weight_only_linear: weight_dtype={weight_dtype!r} not "
            f"supported (int8 only; int4 nibble packing has no TPU path)")
    if group_size != -1:
        raise NotImplementedError(
            "weight_only_linear: group-wise scales not supported "
            "(per-output-channel only)")
    xt, wt = _t(x), _t(weight)
    tensors = [xt, wt]
    if weight_scale is not None:
        st = _t(weight_scale)
        tensors.append(st)
    if bias is not None:
        bt = _t(bias)
        tensors.append(bt)

    def f(a, w, *rest):
        i = 0
        s = None
        if weight_scale is not None:
            s = rest[i]; i += 1
        b = rest[i] if bias is not None else None
        wd = w.astype(a.dtype)
        if s is not None:
            wd = wd * s[None, :].astype(a.dtype)
        out = a @ wd
        if b is not None:
            out = out + b
        return out

    mask = [True, False] + ([False] if weight_scale is not None else []) \
        + ([True] if bias is not None else [])
    return dispatch.call("weight_only_linear", f, tensors,
                         differentiable_mask=mask)


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """LLM.int8() mixed decomposition: columns of ``x`` with outliers
    (|x| > threshold) run in the activation dtype against dequantized
    weights; the rest runs int8xint8 (reference llm_int8_linear op,
    phi/kernels/gpu/llm_int8_linear_kernel.cu).

    On TPU both halves lower to MXU matmuls; the int8 half feeds the MXU's
    8-bit path. weight: (in, out) int8; weight_scale: (out,).
    """
    xt, wt = _t(x), _t(weight)
    use_ste = dispatch.grad_enabled() and not xt.stop_gradient
    tensors = [xt, wt]
    if weight_scale is not None:
        tensors.append(_t(weight_scale))
    if bias is not None:
        tensors.append(_t(bias))

    def f(a, w, *rest):
        i = 0
        s = None
        if weight_scale is not None:
            s = rest[i]; i += 1
        b = rest[i] if bias is not None else None
        outlier = jnp.any(jnp.abs(a) > threshold, axis=tuple(
            range(a.ndim - 1)))                     # (in,) outlier columns
        keep = ~outlier
        # int8 path: quantize the non-outlier activation columns per-row
        a_int = jnp.where(keep[None], a, 0.0) if a.ndim == 2 else \
            jnp.where(keep[(None,) * (a.ndim - 1)], a, 0.0)
        row_scale = jnp.max(jnp.abs(a_int), axis=-1, keepdims=True) / 127.0
        row_scale = jnp.maximum(row_scale, 1e-8)
        aq = jnp.clip(jnp.round(a_int / row_scale), -128, 127).astype(
            jnp.int8)
        int_exact = jax.lax.dot_general(
            aq, w, (((aq.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32).astype(a.dtype) * row_scale
        wd = w.astype(a.dtype)
        if use_ste:
            # straight-through estimator: forward keeps the true int8 MXU
            # matmul; backward flows through the float surrogate so the
            # activation gradient of non-outlier columns is not silently
            # dropped by round/clip's zero derivative. Built only when a
            # gradient can flow — inference pays for the int8 path alone.
            int_surrogate = a_int @ wd
            int_out = int_surrogate + jax.lax.stop_gradient(
                int_exact - int_surrogate)
        else:
            int_out = int_exact
        # fp path for outlier columns against dequantized weight
        a_fp = a - a_int
        fp_out = a_fp @ wd
        out = int_out + fp_out
        if s is not None:
            out = out * s.astype(a.dtype)
        if b is not None:
            out = out + b
        return out

    mask = [True, False] + ([False] if weight_scale is not None else []) \
        + ([True] if bias is not None else [])
    return dispatch.call("llm_int8_linear", f, tensors,
                         differentiable_mask=mask)


def apply_per_channel_scale(x, scales):
    """Divide activations by per-channel smoothing scales (SmoothQuant
    pre-scale; reference apply_per_channel_scale op)."""
    return dispatch.call("apply_per_channel_scale",
                         lambda a, s: a / s, [_t(x), _t(scales)],
                         differentiable_mask=[True, False])


__all__ += ["weight_only_linear", "llm_int8_linear",
            "apply_per_channel_scale"]
