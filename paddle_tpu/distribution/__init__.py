"""paddle.distribution — probability distributions.

Capability parity with the reference distribution package (reference:
python/paddle/distribution/ — Distribution base distribution.py:40, Normal,
Uniform, Categorical, Bernoulli, Beta, Dirichlet, ExponentialFamily,
TransformedDistribution, kl_divergence registry kl.py:34). TPU-native:
sampling uses the framework's counter-based PRNG (reproducible from
``paddle.seed``), log_prob/entropy are jnp expressions through the
dispatcher, so they are differentiable and jit-safe.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.generator import next_key
from ..core.tensor import Tensor, as_tensor


def _t(x):
    if isinstance(x, Tensor):
        return x
    return as_tensor(np.asarray(x, dtype=np.float32))


class Distribution:
    """Base (reference distribution.py:40)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from .. import ops
        return ops.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    """reference distribution/normal.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(self.loc.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return dispatch.call("square", lambda s: s * s, [self.scale])

    @property
    def stddev(self):
        return self.scale

    def sample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(loc, scale):
            eps = jax.random.normal(
                key, shape + loc.shape, dtype=loc.dtype)
            return loc + scale * eps
        with dispatch.no_grad():
            return dispatch.call("normal_sample", f, [self.loc, self.scale])

    def rsample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(loc, scale):
            eps = jax.random.normal(
                key, shape + loc.shape, dtype=loc.dtype)
            return loc + scale * eps
        return dispatch.call("normal_rsample", f, [self.loc, self.scale])

    def log_prob(self, value):
        def f(loc, scale, v):
            var = scale * scale
            return (-((v - loc) ** 2) / (2 * var)
                    - jnp.log(scale) - 0.5 * math.log(2 * math.pi))
        return dispatch.call("normal_log_prob", f,
                             [self.loc, self.scale, _t(value)])

    def entropy(self):
        def f(scale):
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)
        return dispatch.call("normal_entropy", f, [self.scale])


class Uniform(Distribution):
    """reference distribution/uniform.py."""

    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(tuple(self.low.shape))

    @property
    def mean(self):
        return dispatch.call("uniform_mean", lambda l, h: (l + h) / 2,
                             [self.low, self.high])

    @property
    def variance(self):
        return dispatch.call("uniform_var",
                             lambda l, h: (h - l) ** 2 / 12.0,
                             [self.low, self.high])

    def sample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(l, h):
            u = jax.random.uniform(key, shape + l.shape, dtype=l.dtype)
            return l + (h - l) * u
        with dispatch.no_grad():
            return dispatch.call("uniform_sample", f, [self.low, self.high])

    def rsample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(l, h):
            u = jax.random.uniform(key, shape + l.shape, dtype=l.dtype)
            return l + (h - l) * u
        return dispatch.call("uniform_rsample", f, [self.low, self.high])

    def log_prob(self, value):
        def f(l, h, v):
            inside = (v >= l) & (v < h)
            return jnp.where(inside, -jnp.log(h - l), -jnp.inf)
        return dispatch.call("uniform_log_prob", f,
                             [self.low, self.high, _t(value)])

    def entropy(self):
        return dispatch.call("uniform_entropy",
                             lambda l, h: jnp.log(h - l),
                             [self.low, self.high])


class Categorical(Distribution):
    """reference distribution/categorical.py — parameterized by logits
    (unnormalized) like the reference's `logits` arg."""

    def __init__(self, logits, name=None):
        self.logits = _t(logits)
        super().__init__(tuple(self.logits.shape[:-1]))

    @property
    def probs(self):
        return dispatch.call("softmax",
                             lambda l: jax.nn.softmax(l, axis=-1),
                             [self.logits])

    def sample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(logits):
            return jax.random.categorical(
                key, logits, axis=-1,
                shape=shape + logits.shape[:-1])
        with dispatch.no_grad():
            return dispatch.call("categorical_sample", f, [self.logits])

    def log_prob(self, value):
        def f(logits, v):
            logp = jax.nn.log_softmax(logits, axis=-1)
            v = v.astype(jnp.int32)
            # value and batch dims broadcast against each other (value may
            # add leading sample dims, or be size-1 against the batch)
            bshape = jnp.broadcast_shapes(v.shape, logp.shape[:-1])
            logp = jnp.broadcast_to(logp, bshape + logp.shape[-1:])
            v = jnp.broadcast_to(v, bshape)
            return jnp.take_along_axis(logp, v[..., None], axis=-1)[..., 0]
        return dispatch.call("categorical_log_prob", f,
                             [self.logits, _t(value)])

    def entropy(self):
        def f(logits):
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)
        return dispatch.call("categorical_entropy", f, [self.logits])


class Bernoulli(Distribution):
    """reference distribution/bernoulli.py — parameterized by probs."""

    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return dispatch.call("bernoulli_var", lambda p: p * (1 - p),
                             [self.probs])

    def sample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(p):
            return jax.random.bernoulli(
                key, p, shape + p.shape).astype(p.dtype)
        with dispatch.no_grad():
            return dispatch.call("bernoulli_sample", f, [self.probs])

    def log_prob(self, value):
        def f(p, v):
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return dispatch.call("bernoulli_log_prob", f,
                             [self.probs, _t(value)])

    def entropy(self):
        def f(p):
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))
        return dispatch.call("bernoulli_entropy", f, [self.probs])


def kl_divergence(p: Distribution, q: Distribution):
    """reference distribution/kl.py:34 registry; closed forms for the
    registered pairs (register_kl in families.py), Monte-Carlo fallback
    otherwise not provided."""
    from .families import _lookup_kl
    fn = _lookup_kl(p, q)
    if fn is not None:
        return fn(p, q)
    if isinstance(p, Normal) and isinstance(q, Normal):
        def f(l1, s1, l2, s2):
            var1, var2 = s1 * s1, s2 * s2
            return (jnp.log(s2 / s1) + (var1 + (l1 - l2) ** 2) / (2 * var2)
                    - 0.5)
        return dispatch.call("kl_normal_normal", f,
                             [p.loc, p.scale, q.loc, q.scale])
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        def f(l1, h1, l2, h2):
            out = jnp.log((h2 - l2) / (h1 - l1))
            ok = (l2 <= l1) & (h1 <= h2)
            return jnp.where(ok, out, jnp.inf)
        return dispatch.call("kl_uniform_uniform", f,
                             [p.low, p.high, q.low, q.high])
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        def f(lp, lq):
            a = jax.nn.log_softmax(lp, axis=-1)
            b = jax.nn.log_softmax(lq, axis=-1)
            return jnp.sum(jnp.exp(a) * (a - b), axis=-1)
        return dispatch.call("kl_categorical_categorical", f,
                             [p.logits, q.logits])
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        def f(pp, pq):
            eps = 1e-7
            pp = jnp.clip(pp, eps, 1 - eps)
            pq = jnp.clip(pq, eps, 1 - eps)
            return (pp * (jnp.log(pp) - jnp.log(pq))
                    + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-pq)))
        return dispatch.call("kl_bernoulli_bernoulli", f,
                             [p.probs, q.probs])
    raise NotImplementedError(
        f"kl_divergence not registered for "
        f"({type(p).__name__}, {type(q).__name__})")


__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "kl_divergence"]

from .families import *  # noqa: E402,F401,F403
from .lkj_cholesky import LKJCholesky  # noqa: E402
from . import families as _families  # noqa: E402
__all__ += _families.__all__
__all__ += ["LKJCholesky"]
