"""The full distribution family set + transforms + KL registry.

Reference: python/paddle/distribution/{beta,binomial,cauchy,chi2,
continuous_bernoulli,dirichlet,exponential,exponential_family,gamma,
geometric,gumbel,independent,laplace,lognormal,multinomial,
multivariate_normal,poisson,student_t,transform,
transformed_distribution}.py and kl.py (register_kl:63 pairwise registry).

TPU-native: every sampler is a `jax.random.*` draw keyed by the
framework's counter-based PRNG (reproducible under `paddle.seed`, safe
under vmap/jit); log_prob/entropy are jnp expressions through
`dispatch.call`, so they differentiate and fuse. `rsample` is provided
exactly where the reference provides reparameterized gradients.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.generator import next_key
from ..core.tensor import Tensor, as_tensor
from . import (Bernoulli, Categorical, Distribution, Normal,  # noqa: F401
               Uniform, _t)


def _call(name, f, tensors, no_grad=False):
    if no_grad:
        with dispatch.no_grad():
            return dispatch.call(name, f, tensors)
    return dispatch.call(name, f, tensors)


class ExponentialFamily(Distribution):
    """Base for natural-parameter families (reference
    exponential_family.py). entropy() via the Bregman identity when a
    subclass provides natural params + log normalizer."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural):
        raise NotImplementedError


class Exponential(ExponentialFamily):
    """reference exponential.py — rate parameterization."""

    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return _call("exp_mean", lambda r: 1.0 / r, [self.rate])

    @property
    def variance(self):
        return _call("exp_var", lambda r: 1.0 / (r * r), [self.rate])

    def sample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(r):
            return jax.random.exponential(
                key, shape + r.shape, dtype=r.dtype) / r

        return _call("exp_sample", f, [self.rate], no_grad=True)

    def rsample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(r):
            return jax.random.exponential(
                key, shape + r.shape, dtype=r.dtype) / r

        return _call("exp_rsample", f, [self.rate])

    def log_prob(self, value):
        return _call("exp_log_prob",
                     lambda r, v: jnp.where(v >= 0, jnp.log(r) - r * v,
                                            -jnp.inf),
                     [self.rate, _t(value)])

    def entropy(self):
        return _call("exp_entropy", lambda r: 1.0 - jnp.log(r), [self.rate])

    def cdf(self, value):
        return _call("exp_cdf",
                     lambda r, v: jnp.clip(1 - jnp.exp(-r * v), 0, 1),
                     [self.rate, _t(value)])

    def icdf(self, value):
        return _call("exp_icdf", lambda r, u: -jnp.log1p(-u) / r,
                     [self.rate, _t(value)])


class Gamma(ExponentialFamily):
    """reference gamma.py — (concentration, rate)."""

    def __init__(self, concentration, rate, name=None):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.concentration._data.shape, self.rate._data.shape)))

    @property
    def mean(self):
        return _call("gamma_mean", lambda a, r: a / r,
                     [self.concentration, self.rate])

    @property
    def variance(self):
        return _call("gamma_var", lambda a, r: a / (r * r),
                     [self.concentration, self.rate])

    def sample(self, shape=()):
        with dispatch.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(a, r):
            a_b, r_b = jnp.broadcast_arrays(a, r)
            return jax.random.gamma(key, a_b, shape + a_b.shape,
                                    dtype=a.dtype) / r_b

        return _call("gamma_rsample", f, [self.concentration, self.rate])

    def log_prob(self, value):
        def f(a, r, v):
            return (a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v
                    - jax.scipy.special.gammaln(a))

        return _call("gamma_log_prob", f,
                     [self.concentration, self.rate, _t(value)])

    def entropy(self):
        def f(a, r):
            return (a - jnp.log(r) + jax.scipy.special.gammaln(a)
                    + (1 - a) * jax.scipy.special.digamma(a))

        return _call("gamma_entropy", f, [self.concentration, self.rate])


class Chi2(Gamma):
    """reference chi2.py — Gamma(df/2, 1/2)."""

    def __init__(self, df, name=None):
        self.df = _t(df)
        super().__init__(
            dispatch.call("chi2_a", lambda d: d / 2.0, [self.df]),
            as_tensor(np.float32(0.5)))


class Beta(ExponentialFamily):
    """reference beta.py — (alpha, beta)."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.alpha._data.shape, self.beta._data.shape)))

    @property
    def mean(self):
        return _call("beta_mean", lambda a, b: a / (a + b),
                     [self.alpha, self.beta])

    @property
    def variance(self):
        return _call("beta_var",
                     lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)),
                     [self.alpha, self.beta])

    def sample(self, shape=()):
        with dispatch.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(a, b):
            a_b, b_b = jnp.broadcast_arrays(a, b)
            return jax.random.beta(key, a_b, b_b, shape + a_b.shape,
                                   dtype=a.dtype)

        return _call("beta_rsample", f, [self.alpha, self.beta])

    def log_prob(self, value):
        def f(a, b, v):
            return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                    - jax.scipy.special.betaln(a, b))

        return _call("beta_log_prob", f, [self.alpha, self.beta, _t(value)])

    def entropy(self):
        def f(a, b):
            dg = jax.scipy.special.digamma
            return (jax.scipy.special.betaln(a, b)
                    - (a - 1) * dg(a) - (b - 1) * dg(b)
                    + (a + b - 2) * dg(a + b))

        return _call("beta_entropy", f, [self.alpha, self.beta])


class Dirichlet(ExponentialFamily):
    """reference dirichlet.py."""

    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        shp = tuple(self.concentration.shape)
        super().__init__(shp[:-1], shp[-1:])

    @property
    def mean(self):
        return _call("dir_mean",
                     lambda c: c / jnp.sum(c, -1, keepdims=True),
                     [self.concentration])

    @property
    def variance(self):
        def f(c):
            c0 = jnp.sum(c, -1, keepdims=True)
            m = c / c0
            return m * (1 - m) / (c0 + 1)

        return _call("dir_var", f, [self.concentration])

    def sample(self, shape=()):
        with dispatch.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(c):
            return jax.random.dirichlet(key, c, shape + c.shape[:-1],
                                        dtype=c.dtype)

        return _call("dir_rsample", f, [self.concentration])

    def log_prob(self, value):
        def f(c, v):
            return (jnp.sum((c - 1) * jnp.log(v), -1)
                    + jax.scipy.special.gammaln(jnp.sum(c, -1))
                    - jnp.sum(jax.scipy.special.gammaln(c), -1))

        return _call("dir_log_prob", f, [self.concentration, _t(value)])

    def entropy(self):
        def f(c):
            gl, dg = jax.scipy.special.gammaln, jax.scipy.special.digamma
            c0 = jnp.sum(c, -1)
            k = c.shape[-1]
            return (jnp.sum(gl(c), -1) - gl(c0)
                    + (c0 - k) * dg(c0)
                    - jnp.sum((c - 1) * dg(c), -1))

        return _call("dir_entropy", f, [self.concentration])


class Laplace(Distribution):
    """reference laplace.py — (loc, scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape)))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return _call("lap_var", lambda s: 2 * s * s, [self.scale])

    @property
    def stddev(self):
        return _call("lap_std", lambda s: math.sqrt(2.0) * s, [self.scale])

    def sample(self, shape=()):
        with dispatch.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(l, s):
            l_b, s_b = jnp.broadcast_arrays(l, s)
            eps = jax.random.laplace(key, shape + l_b.shape, dtype=l.dtype)
            return l_b + s_b * eps

        return _call("lap_rsample", f, [self.loc, self.scale])

    def log_prob(self, value):
        return _call("lap_log_prob",
                     lambda l, s, v: -jnp.abs(v - l) / s - jnp.log(2 * s),
                     [self.loc, self.scale, _t(value)])

    def entropy(self):
        return _call("lap_entropy", lambda s: 1 + jnp.log(2 * s),
                     [self.scale])

    def cdf(self, value):
        def f(l, s, v):
            z = (v - l) / s
            return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))

        return _call("lap_cdf", f, [self.loc, self.scale, _t(value)])

    def icdf(self, value):
        def f(l, s, u):
            return l - s * jnp.sign(u - 0.5) * jnp.log1p(-2 * jnp.abs(u - 0.5))

        return _call("lap_icdf", f, [self.loc, self.scale, _t(value)])


class Cauchy(Distribution):
    """reference cauchy.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape)))

    def sample(self, shape=()):
        with dispatch.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(l, s):
            l_b, s_b = jnp.broadcast_arrays(l, s)
            eps = jax.random.cauchy(key, shape + l_b.shape, dtype=l.dtype)
            return l_b + s_b * eps

        return _call("cauchy_rsample", f, [self.loc, self.scale])

    def log_prob(self, value):
        def f(l, s, v):
            z = (v - l) / s
            return -jnp.log(math.pi * s * (1 + z * z))

        return _call("cauchy_log_prob", f,
                     [self.loc, self.scale, _t(value)])

    def entropy(self):
        return _call("cauchy_entropy",
                     lambda s: jnp.log(4 * math.pi * s), [self.scale])

    def cdf(self, value):
        def f(l, s, v):
            return jnp.arctan((v - l) / s) / math.pi + 0.5

        return _call("cauchy_cdf", f, [self.loc, self.scale, _t(value)])


class Gumbel(Distribution):
    """reference gumbel.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape)))

    @property
    def mean(self):
        return _call("gumbel_mean",
                     lambda l, s: l + np.float32(np.euler_gamma) * s,
                     [self.loc, self.scale])

    @property
    def variance(self):
        return _call("gumbel_var",
                     lambda s: (math.pi ** 2 / 6) * s * s, [self.scale])

    def sample(self, shape=()):
        with dispatch.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(l, s):
            l_b, s_b = jnp.broadcast_arrays(l, s)
            eps = jax.random.gumbel(key, shape + l_b.shape, dtype=l.dtype)
            return l_b + s_b * eps

        return _call("gumbel_rsample", f, [self.loc, self.scale])

    def log_prob(self, value):
        def f(l, s, v):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)

        return _call("gumbel_log_prob", f,
                     [self.loc, self.scale, _t(value)])

    def entropy(self):
        return _call("gumbel_entropy",
                     lambda s: jnp.log(s) + 1 + np.float32(np.euler_gamma),
                     [self.scale])


class LogNormal(Distribution):
    """reference lognormal.py — exp(Normal(loc, scale))."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape)))

    @property
    def mean(self):
        return _call("lognorm_mean",
                     lambda l, s: jnp.exp(l + s * s / 2),
                     [self.loc, self.scale])

    @property
    def variance(self):
        def f(l, s):
            return (jnp.exp(s * s) - 1) * jnp.exp(2 * l + s * s)

        return _call("lognorm_var", f, [self.loc, self.scale])

    def sample(self, shape=()):
        with dispatch.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(l, s):
            l_b, s_b = jnp.broadcast_arrays(l, s)
            eps = jax.random.normal(key, shape + l_b.shape, dtype=l.dtype)
            return jnp.exp(l_b + s_b * eps)

        return _call("lognorm_rsample", f, [self.loc, self.scale])

    def log_prob(self, value):
        def f(l, s, v):
            logv = jnp.log(v)
            return (-((logv - l) ** 2) / (2 * s * s) - logv
                    - jnp.log(s) - 0.5 * math.log(2 * math.pi))

        return _call("lognorm_log_prob", f,
                     [self.loc, self.scale, _t(value)])

    def entropy(self):
        return _call("lognorm_entropy",
                     lambda l, s: 0.5 + 0.5 * math.log(2 * math.pi)
                     + jnp.log(s) + l,
                     [self.loc, self.scale])


class Geometric(Distribution):
    """reference geometric.py — #failures before first success, support
    {0, 1, ...}, parameterized by success prob."""

    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return _call("geom_mean", lambda p: (1 - p) / p, [self.probs])

    @property
    def variance(self):
        return _call("geom_var", lambda p: (1 - p) / (p * p), [self.probs])

    def sample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(p):
            u = jax.random.uniform(key, shape + p.shape, dtype=p.dtype,
                                   minval=1e-12)
            return jnp.floor(jnp.log(u) / jnp.log1p(-p))

        return _call("geom_sample", f, [self.probs], no_grad=True)

    def log_prob(self, value):
        return _call("geom_log_prob",
                     lambda p, v: v * jnp.log1p(-p) + jnp.log(p),
                     [self.probs, _t(value)])

    def entropy(self):
        def f(p):
            q = 1 - p
            return -(q * jnp.log(q) + p * jnp.log(p)) / p

        return _call("geom_entropy", f, [self.probs])


class Poisson(Distribution):
    """reference poisson.py — rate parameterization."""

    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(r):
            return jax.random.poisson(key, r, shape + r.shape).astype(
                r.dtype)

        return _call("poisson_sample", f, [self.rate], no_grad=True)

    def log_prob(self, value):
        def f(r, v):
            return (v * jnp.log(r) - r
                    - jax.scipy.special.gammaln(v + 1))

        return _call("poisson_log_prob", f, [self.rate, _t(value)])


class Binomial(Distribution):
    """reference binomial.py — (total_count, probs)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = _t(total_count)
        self.probs = _t(probs)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.total_count._data.shape, self.probs._data.shape)))

    @property
    def mean(self):
        return _call("binom_mean", lambda n, p: n * p,
                     [self.total_count, self.probs])

    @property
    def variance(self):
        return _call("binom_var", lambda n, p: n * p * (1 - p),
                     [self.total_count, self.probs])

    def sample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(n, p):
            n_b, p_b = jnp.broadcast_arrays(n, p)
            return jax.random.binomial(key, n_b, p_b,
                                       shape + n_b.shape).astype(p.dtype)

        return _call("binom_sample", f, [self.total_count, self.probs],
                     no_grad=True)

    def log_prob(self, value):
        def f(n, p, v):
            gl = jax.scipy.special.gammaln
            logc = gl(n + 1) - gl(v + 1) - gl(n - v + 1)
            return logc + v * jnp.log(p) + (n - v) * jnp.log1p(-p)

        return _call("binom_log_prob", f,
                     [self.total_count, self.probs, _t(value)])


class Multinomial(Distribution):
    """reference multinomial.py — (total_count, probs over last axis)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        shp = tuple(self.probs.shape)
        super().__init__(shp[:-1], shp[-1:])

    @property
    def mean(self):
        return _call("multi_mean", lambda p: self.total_count * p,
                     [self.probs])

    @property
    def variance(self):
        return _call("multi_var",
                     lambda p: self.total_count * p * (1 - p),
                     [self.probs])

    def sample(self, shape=()):
        key = next_key()
        shape = tuple(shape)
        n = self.total_count

        def f(p):
            if hasattr(jax.random, "multinomial"):
                return jax.random.multinomial(
                    key, jnp.asarray(float(n), p.dtype), p,
                    shape=shape + p.shape).astype(p.dtype)
            # older jax: n categorical draws + one-hot count per bucket
            # is the same distribution (batch dims broadcast over the
            # leading sample axis)
            draws = jax.random.categorical(
                key, jnp.log(jnp.clip(p, 1e-38, None)),
                shape=(int(n),) + shape + p.shape[:-1])
            counts = jax.nn.one_hot(draws, p.shape[-1],
                                    dtype=p.dtype).sum(axis=0)
            return counts

        return _call("multi_sample", f, [self.probs], no_grad=True)

    def log_prob(self, value):
        n = float(self.total_count)

        def f(p, v):
            gl = jax.scipy.special.gammaln
            return (gl(n + 1) - jnp.sum(gl(v + 1), -1)
                    + jnp.sum(v * jnp.log(p), -1))

        return _call("multi_log_prob", f, [self.probs, _t(value)])


class StudentT(Distribution):
    """reference student_t.py — (df, loc, scale)."""

    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _t(df)
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(tuple(jnp.broadcast_shapes(
            self.df._data.shape, self.loc._data.shape,
            self.scale._data.shape)))

    @property
    def mean(self):
        return _call("t_mean",
                     lambda d, l: jnp.where(d > 1, l, jnp.nan),
                     [self.df, self.loc])

    @property
    def variance(self):
        def f(d, s):
            v = s * s * d / (d - 2)
            return jnp.where(d > 2, v, jnp.where(d > 1, jnp.inf, jnp.nan))

        return _call("t_var", f, [self.df, self.scale])

    def sample(self, shape=()):
        with dispatch.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(d, l, s):
            d_b, l_b, s_b = jnp.broadcast_arrays(d, l, s)
            eps = jax.random.t(key, d_b, shape + d_b.shape, dtype=l.dtype)
            return l_b + s_b * eps

        return _call("t_rsample", f, [self.df, self.loc, self.scale])

    def log_prob(self, value):
        def f(d, l, s, v):
            gl = jax.scipy.special.gammaln
            z = (v - l) / s
            return (gl((d + 1) / 2) - gl(d / 2)
                    - 0.5 * jnp.log(d * math.pi) - jnp.log(s)
                    - (d + 1) / 2 * jnp.log1p(z * z / d))

        return _call("t_log_prob", f,
                     [self.df, self.loc, self.scale, _t(value)])


class MultivariateNormal(Distribution):
    """reference multivariate_normal.py — (loc, covariance_matrix)."""

    def __init__(self, loc, covariance_matrix=None, scale_tril=None,
                 name=None):
        self.loc = _t(loc)
        if (covariance_matrix is None) == (scale_tril is None):
            raise ValueError(
                "provide exactly one of covariance_matrix / scale_tril")
        if scale_tril is not None:
            self.scale_tril = _t(scale_tril)
            self.covariance_matrix = dispatch.call(
                "mvn_cov", lambda L: L @ jnp.swapaxes(L, -1, -2),
                [self.scale_tril])
        else:
            self.covariance_matrix = _t(covariance_matrix)
            self.scale_tril = dispatch.call(
                "mvn_chol", jnp.linalg.cholesky, [self.covariance_matrix])
        shp = tuple(self.loc.shape)
        super().__init__(shp[:-1], shp[-1:])

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return _call("mvn_var",
                     lambda c: jnp.diagonal(c, axis1=-2, axis2=-1),
                     [self.covariance_matrix])

    def sample(self, shape=()):
        with dispatch.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(l, L):
            eps = jax.random.normal(key, shape + l.shape, dtype=l.dtype)
            return l + jnp.einsum("...ij,...j->...i", L, eps)

        return _call("mvn_rsample", f, [self.loc, self.scale_tril])

    def log_prob(self, value):
        def f(l, L, v):
            d = l.shape[-1]
            diff = v - l
            sol = jax.scipy.linalg.solve_triangular(L, diff[..., None],
                                                    lower=True)[..., 0]
            logdet = jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)),
                             -1)
            return (-0.5 * jnp.sum(sol * sol, -1) - logdet
                    - 0.5 * d * math.log(2 * math.pi))

        return _call("mvn_log_prob", f,
                     [self.loc, self.scale_tril, _t(value)])

    def entropy(self):
        def f(L):
            d = L.shape[-1]
            logdet = jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)),
                             -1)
            return 0.5 * d * (1 + math.log(2 * math.pi)) + logdet

        return _call("mvn_entropy", f, [self.scale_tril])


class ContinuousBernoulli(Distribution):
    """reference continuous_bernoulli.py — CB(probs) on [0, 1]."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _t(probs)
        self._lims = lims
        super().__init__(tuple(self.probs.shape))

    def _log_norm(self, p):
        # C(p) = 2 atanh(1-2p) / (1-2p), with the p=0.5 limit -> log 2
        near = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(near, 0.25, p)
        c = (jnp.log(jnp.abs(jnp.arctanh(1 - 2 * safe)))
             + jnp.log(2.0) - jnp.log(jnp.abs(1 - 2 * safe)))
        # Taylor around 0.5: log C ~ log 2 + 4/3 (p-1/2)^2
        taylor = math.log(2.0) + 4.0 / 3.0 * (p - 0.5) ** 2
        return jnp.where(near, taylor, c)

    def log_prob(self, value):
        def f(p, v):
            return (v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
                    + self._log_norm(p))

        return _call("cb_log_prob", f, [self.probs, _t(value)])

    def sample(self, shape=()):
        key = next_key()
        shape = tuple(shape)

        def f(p):
            u = jax.random.uniform(key, shape + p.shape, dtype=p.dtype,
                                   minval=1e-6, maxval=1 - 1e-6)
            # inverse CDF: x = (log1p(u(p/(1-p) - 1) ... ) standard CB icdf
            q = 1 - p
            near = (p > self._lims[0]) & (p < self._lims[1])
            safe_p = jnp.where(near, 0.25, p)
            safe_q = 1 - safe_p
            x = (jnp.log1p(u * (safe_p / safe_q - 1))
                 / (jnp.log(safe_p) - jnp.log(safe_q)))
            return jnp.where(near, u, x)

        return _call("cb_sample", f, [self.probs], no_grad=True)

    @property
    def mean(self):
        def f(p):
            near = (p > self._lims[0]) & (p < self._lims[1])
            safe = jnp.where(near, 0.25, p)
            m = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
            return jnp.where(near, 0.5, m)

        return _call("cb_mean", f, [self.probs])


class Independent(Distribution):
    """reference independent.py — reinterpret batch dims as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bshape = base.batch_shape
        super().__init__(bshape[:len(bshape) - self.rank],
                         bshape[len(bshape) - self.rank:]
                         + tuple(base.event_shape))

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        return dispatch.call(
            "independent_sum",
            lambda a: jnp.sum(a, axis=tuple(range(a.ndim - self.rank,
                                                  a.ndim))), [lp])

    def entropy(self):
        ent = self.base.entropy()
        return dispatch.call(
            "independent_ent_sum",
            lambda a: jnp.sum(a, axis=tuple(range(a.ndim - self.rank,
                                                  a.ndim))), [ent])


# --------------------------- transforms ------------------------------
class Transform:
    """reference transform.py Transform — forward/inverse +
    log|det J|."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        neg = self.forward_log_det_jacobian(self.inverse(y))
        return dispatch.call("t_neg", lambda a: -a, [neg])

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def forward(self, x):
        return _call("affine_fwd", lambda l, s, x: l + s * x,
                     [self.loc, self.scale, _t(x)])

    def inverse(self, y):
        return _call("affine_inv", lambda l, s, y: (y - l) / s,
                     [self.loc, self.scale, _t(y)])

    def forward_log_det_jacobian(self, x):
        def f(s, x):
            return jnp.broadcast_to(jnp.log(jnp.abs(s)), x.shape)

        return _call("affine_ldj", f, [self.scale, _t(x)])


class ExpTransform(Transform):
    def forward(self, x):
        return _call("expt_fwd", jnp.exp, [_t(x)])

    def inverse(self, y):
        return _call("expt_inv", jnp.log, [_t(y)])

    def forward_log_det_jacobian(self, x):
        return _t(x)


class SigmoidTransform(Transform):
    def forward(self, x):
        return _call("sig_fwd", jax.nn.sigmoid, [_t(x)])

    def inverse(self, y):
        return _call("sig_inv", lambda y: jnp.log(y) - jnp.log1p(-y),
                     [_t(y)])

    def forward_log_det_jacobian(self, x):
        return _call("sig_ldj",
                     lambda x: -jax.nn.softplus(-x) - jax.nn.softplus(x),
                     [_t(x)])


class TanhTransform(Transform):
    def forward(self, x):
        return _call("tanh_fwd", jnp.tanh, [_t(x)])

    def inverse(self, y):
        return _call("tanh_inv", jnp.arctanh, [_t(y)])

    def forward_log_det_jacobian(self, x):
        return _call("tanh_ldj",
                     lambda x: 2 * (math.log(2.0) - x
                                    - jax.nn.softplus(-2 * x)), [_t(x)])


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _t(power)

    def forward(self, x):
        return _call("pow_fwd", lambda p, x: jnp.power(x, p),
                     [self.power, _t(x)])

    def inverse(self, y):
        return _call("pow_inv", lambda p, y: jnp.power(y, 1.0 / p),
                     [self.power, _t(y)])

    def forward_log_det_jacobian(self, x):
        return _call("pow_ldj",
                     lambda p, x: jnp.log(jnp.abs(p * jnp.power(x, p - 1))),
                     [self.power, _t(x)])


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            ldj = t.forward_log_det_jacobian(x)
            total = ldj if total is None else dispatch.call(
                "chain_add", lambda a, b: a + b, [total, ldj])
            x = t.forward(x)
        return total


class TransformedDistribution(Distribution):
    """reference transformed_distribution.py."""

    def __init__(self, base, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transforms = list(transforms)
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        y = _t(value)
        lp_terms = []
        for t in reversed(self.transforms):
            x = t.inverse(y)
            lp_terms.append(t.forward_log_det_jacobian(x))
            y = x
        lp = self.base.log_prob(y)
        for term in lp_terms:
            lp = dispatch.call("td_sub", lambda a, b: a - b, [lp, term])
        return lp


# --------------------------- KL registry ------------------------------
_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    """reference kl.py:63 — decorator registering a closed-form KL."""

    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn

    return deco


def _lookup_kl(p, q):
    # most-derived match first (reference dispatches on exact class then
    # walks the MRO)
    best = None
    for (tp, tq), fn in _KL_REGISTRY.items():
        if isinstance(p, tp) and isinstance(q, tq):
            if best is None or (issubclass(tp, best[0])
                                and issubclass(tq, best[1])):
                best = (tp, tq, fn)
    return best[2] if best else None


@register_kl(Exponential, Exponential)
def _kl_exp(p, q):
    return _call("kl_exp",
                 lambda r1, r2: jnp.log(r1) - jnp.log(r2) + r2 / r1 - 1,
                 [p.rate, q.rate])


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    def f(a1, r1, a2, r2):
        gl, dg = jax.scipy.special.gammaln, jax.scipy.special.digamma
        return ((a1 - a2) * dg(a1) - gl(a1) + gl(a2)
                + a2 * (jnp.log(r1) - jnp.log(r2))
                + a1 * (r2 - r1) / r1)

    return _call("kl_gamma", f,
                 [p.concentration, p.rate, q.concentration, q.rate])


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    def f(a1, b1, a2, b2):
        gl, dg = jax.scipy.special.betaln, jax.scipy.special.digamma
        return (gl(a2, b2) - gl(a1, b1)
                + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
                + (a2 - a1 + b2 - b1) * dg(a1 + b1))

    return _call("kl_beta", f, [p.alpha, p.beta, q.alpha, q.beta])


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    def f(c1, c2):
        gl, dg = jax.scipy.special.gammaln, jax.scipy.special.digamma
        s1 = jnp.sum(c1, -1)
        return (gl(s1) - jnp.sum(gl(c1), -1)
                - jax.scipy.special.gammaln(jnp.sum(c2, -1))
                + jnp.sum(gl(c2), -1)
                + jnp.sum((c1 - c2) * (dg(c1) - dg(s1)[..., None]), -1))

    return _call("kl_dirichlet", f, [p.concentration, q.concentration])


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    def f(l1, s1, l2, s2):
        d = jnp.abs(l1 - l2)
        return (jnp.log(s2 / s1) + d / s2
                + s1 / s2 * jnp.exp(-d / s1) - 1)

    return _call("kl_laplace", f, [p.loc, p.scale, q.loc, q.scale])


@register_kl(Geometric, Geometric)
def _kl_geometric(p, q):
    def f(p1, p2):
        return ((1 - p1) / p1 * (jnp.log1p(-p1) - jnp.log1p(-p2))
                + jnp.log(p1) - jnp.log(p2))

    return _call("kl_geom", f, [p.probs, q.probs])


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    return _call("kl_poisson",
                 lambda r1, r2: r1 * (jnp.log(r1) - jnp.log(r2))
                 + r2 - r1,
                 [p.rate, q.rate])


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    def f(l1, L1, l2, L2):
        d = l1.shape[-1]
        M = jax.scipy.linalg.solve_triangular(L2, L1, lower=True)
        tr = jnp.sum(M * M, axis=(-2, -1))
        diff = l2 - l1
        sol = jax.scipy.linalg.solve_triangular(L2, diff[..., None],
                                                lower=True)[..., 0]
        maha = jnp.sum(sol * sol, -1)
        logdet = (jnp.sum(jnp.log(jnp.diagonal(L2, axis1=-2, axis2=-1)), -1)
                  - jnp.sum(jnp.log(jnp.diagonal(L1, axis1=-2, axis2=-1)),
                            -1))
        return 0.5 * (tr + maha - d) + logdet

    return _call("kl_mvn", f, [p.loc, p.scale_tril, q.loc, q.scale_tril])


__all__ = [
    "ExponentialFamily", "Exponential", "Gamma", "Chi2", "Beta",
    "Dirichlet", "Laplace", "Cauchy", "Gumbel", "LogNormal", "Geometric",
    "Poisson", "Binomial", "Multinomial", "StudentT",
    "MultivariateNormal", "ContinuousBernoulli", "Independent",
    "Transform", "AffineTransform", "ExpTransform", "SigmoidTransform",
    "TanhTransform", "PowerTransform", "ChainTransform",
    "TransformedDistribution", "register_kl",
]
