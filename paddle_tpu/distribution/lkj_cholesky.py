"""LKJCholesky: distribution over Cholesky factors of correlation
matrices.

Reference contract: ``python/paddle/distribution/lkj_cholesky.py``
(LKJCholesky :119 — Lewandowski, Kurowicka & Joe 2009; 'onion' and
'cvine' samplers built from per-row marginal Beta draws :142-320;
log_prob with the mvlgamma normalizer :337-372).
"""
from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, as_tensor
from . import Distribution
from .families import Beta

__all__ = ["LKJCholesky"]


def _key():
    from ..core.generator import next_key
    return next_key()


def _mvlgamma(a, p):
    """Multivariate log-gamma (order p)."""
    from jax.scipy.special import gammaln
    a = jnp.asarray(a)[..., None]
    js = jnp.arange(p, dtype=a.dtype)
    return (p * (p - 1) / 4.0 * math.log(math.pi)
            + gammaln(a - 0.5 * js).sum(-1))


class LKJCholesky(Distribution):
    def __init__(self, dim=2, concentration=1.0, sample_method="onion"):
        if not isinstance(dim, int):
            raise TypeError(f"Expected dim to be an integer. Found "
                            f"dim={dim}.")
        if dim < 2:
            raise ValueError(
                f"Expected dim greater than or equal to 2. Found "
                f"dim={dim}.")
        conc = as_tensor(concentration)._data.astype(jnp.float32)
        if conc.ndim == 0:
            conc = conc[None]
        if not bool((conc > 0).all()):  # tpulint: disable=TPU103 — constructor-time argument validation: one host read at distribution build, never per-step
            raise ValueError("The arg of `concentration` must be "
                             "positive.")
        self.dim = dim
        self.concentration = Tensor(conc)
        self.sample_method = sample_method

        marginal = conc + 0.5 * (dim - 2)
        offset = jnp.arange(dim - 1, dtype=conc.dtype)
        if sample_method == "onion":
            off = jnp.concatenate([jnp.zeros((1,), conc.dtype), offset])
            self._beta = Beta(Tensor(off + 0.5),
                              Tensor(marginal[..., None] - 0.5 * off))
        elif sample_method == "cvine":
            tril = jnp.tril(jnp.broadcast_to(
                0.5 * offset, (dim - 1, dim - 1)))
            bc = marginal[..., None, None] - tril
            self._beta = Beta(Tensor(bc), Tensor(bc))
        else:
            raise ValueError(
                "`method` should be one of 'cvine' or 'onion'.")
        super().__init__(tuple(conc.shape), (dim, dim))

    # ----------------------------------------------------------- sampling
    def _onion(self, sample_shape):
        y = self._beta.sample(sample_shape)._data[..., None]
        shape = tuple(sample_shape) + self._batch_shape \
            + self._event_shape
        u = jnp.tril(jax.random.normal(_key(), shape, jnp.float32), -1)
        norm = jnp.linalg.norm(u, axis=-1, keepdims=True)
        u_hyper = u / jnp.where(norm == 0, 1.0, norm)
        # row 0 has no off-diagonal mass
        u_hyper = u_hyper.at[..., 0, :].set(0.0)
        w = jnp.sqrt(y) * u_hyper
        tiny = jnp.finfo(w.dtype).tiny
        diag = jnp.sqrt(jnp.clip(1 - (w * w).sum(-1), tiny))
        return w + jnp.vectorize(jnp.diag,
                                 signature="(n)->(n,n)")(diag)

    def _cvine(self, sample_shape):
        b = self._beta.sample(sample_shape)._data
        pc = 2 * b - 1                     # partial correlations (tril)
        d = self.dim
        # embed the (d-1)x(d-1) lower-tri block below the diagonal
        z = jnp.zeros(tuple(pc.shape[:-2]) + (d, d), pc.dtype)
        r = z.at[..., 1:, :-1].set(jnp.tril(pc))
        tiny = jnp.finfo(r.dtype).tiny
        r = jnp.clip(r, -1 + tiny, 1 - tiny)
        cum = jnp.cumprod(jnp.sqrt(1 - r * r), axis=-1)
        shifted = jnp.concatenate(
            [jnp.ones(cum.shape[:-1] + (1,), cum.dtype), cum[..., :-1]],
            axis=-1)
        eye = jnp.eye(d, dtype=r.dtype)
        return (r + eye) * shifted

    def sample(self, sample_shape=()):
        if not isinstance(sample_shape, Sequence):
            raise TypeError("sample shape must be Sequence object.")
        shape = tuple(sample_shape) or (1,)
        out = (self._onion(shape) if self.sample_method == "onion"
               else self._cvine(shape))
        return Tensor(out)

    # ------------------------------------------------------------ density
    def log_prob(self, value):
        v = as_tensor(value)._data
        conc = self.concentration._data
        diag = jnp.diagonal(v, axis1=-2, axis2=-1)[..., 1:]
        order = jnp.arange(2, self.dim + 1, dtype=conc.dtype)
        order = 2 * (conc - 1)[..., None] + self.dim - order
        unnorm = (order * jnp.log(diag)).sum(-1)
        dm1 = self.dim - 1
        alpha = conc + 0.5 * dm1
        from jax.scipy.special import gammaln
        denominator = gammaln(alpha) * dm1
        numerator = _mvlgamma(alpha - 0.5, dm1)
        pi_constant = 0.5 * dm1 * math.log(math.pi)
        return Tensor(unnorm - (pi_constant + numerator - denominator))
