"""paddle.hub — re-export of the hapi hub implementation (reference:
python/paddle/hub.py delegating to hapi/hub.py)."""
from .hapi.hub import help, list, load  # noqa: F401

__all__ = ["list", "help", "load"]
