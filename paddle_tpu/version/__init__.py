"""paddle.version — version metadata (reference: python/paddle/version
generated at build time; fields mirrored here for API parity)."""

full_version = "3.0.0+tpu"
major = "3"
minor = "0"
patch = "0"
rc = "0"
commit = "tpu-native"
with_gpu = "OFF"
with_tpu = "ON"
cuda_version = "False"
cudnn_version = "False"
istaged = False


def show():
    """Print version info (reference version.show())."""
    print(f"full_version: {full_version}")
    print(f"major: {major}")
    print(f"minor: {minor}")
    print(f"patch: {patch}")
    print(f"commit: {commit}")
    print(f"with_tpu: {with_tpu}")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version


def xpu():
    return "False"
