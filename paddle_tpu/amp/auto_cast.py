"""auto_cast / decorate (reference: python/paddle/amp/auto_cast.py)."""
from __future__ import annotations

from ..core import dispatch
from ..core import dtype as dtypes


class auto_cast:
    """Context manager: O1 casts white-list op inputs to the amp dtype at
    dispatch time; O2 additionally assumes params were cast by decorate()."""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="bfloat16",
                 use_promote=True):
        if level not in ("O0", "O1", "O2", "OD"):
            raise ValueError(f"unsupported amp level {level}")
        self.enable = enable
        self.level = level if enable else "O0"
        self.dtype = dtype
        self.custom_white_list = custom_white_list
        self.custom_black_list = custom_black_list

    def __enter__(self):
        self._prev = dispatch.set_amp_state(
            self.level, self.dtype, self.custom_white_list,
            self.custom_black_list)
        return self

    def __exit__(self, *exc):
        dispatch.restore_amp_state(self._prev)
        return False


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """O2: cast model params to the amp dtype, keeping master fp32 copies in
    the optimizer (reference amp.decorate)."""
    if level == "O1" or level == "O0":
        return (models, optimizers) if optimizers is not None else models
    target = dtypes.convert_dtype(dtype)
    model_list = models if isinstance(models, (list, tuple)) else [models]
    excluded = tuple(excluded_layers) if excluded_layers else ()
    from ..nn.layer.norm import _BatchNormBase, LayerNorm

    for model in model_list:
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, (_BatchNormBase, LayerNorm)) or \
                    (excluded and isinstance(layer, excluded)):
                continue  # norm layers stay fp32 for numeric stability
            for pname, p in layer._parameters.items():
                if p is None:
                    continue
                cur = p.dtype
                import numpy as np
                if np.issubdtype(cur, np.floating) or cur == dtypes.bfloat16:
                    p._swap_payload(p._data.astype(target))
            layer._casted_by_pure_fp16 = True
    if optimizers is not None:
        opt_list = (optimizers if isinstance(optimizers, (list, tuple))
                    else [optimizers])
        for opt in opt_list:
            opt._multi_precision = True
        return (models if isinstance(models, (list, tuple)) else model_list[0],
                optimizers)
    return models if isinstance(models, (list, tuple)) else model_list[0]


amp_decorate = decorate
