"""Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py).

bf16 needs no loss scaling (same exponent range as fp32) so ``enable=False``
is the common TPU path; the fp16 machinery is complete for parity: scale the
loss, unscale grads at step, skip steps whose grads contain inf/nan, and
grow/shrink the scale on the usual schedule.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor
from ..observability import metrics as _metrics

_m_found_inf = _metrics.counter(
    "paddle_tpu_amp_found_inf_total",
    "Optimizer steps skipped because unscaled grads contained inf/nan.")


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=65536.0,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def scale(self, var):
        if not self._enable:
            return var
        return dispatch.call("loss_scale", lambda a: a * self._scale, [var])

    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        inv = 1.0 / self._scale
        # per-leaf scalar any() reductions stay on device; ONE stacked
        # reduction and ONE host transfer decide the whole step (the old
        # path synced the host once per gradient leaf)
        flags = []
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._data.astype(jnp.float32) * inv
            flags.append(jnp.any(~jnp.isfinite(g)))
            p.grad._swap_payload(g.astype(p.grad._data.dtype))
        found = bool(jnp.any(jnp.stack(flags))) if flags else False  # tpulint: disable=TPU103 — THE one host sync: step/skip is a host-side control decision
        if found:
            _m_found_inf.inc()
        self._found_inf = found
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._update_scale()
        self._unscaled = False

    def update(self):
        """No-op hook for API parity; scale update happens in step()."""

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        optimizer.clear_grad()

    def _update_scale(self):
        if not self._dynamic:
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self):
        return {
            "scale": self._scale,
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
            "use_dynamic_loss_scaling": self._dynamic,
        }

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)
        self._dynamic = state.get("use_dynamic_loss_scaling", self._dynamic)

    set_state_dict = load_state_dict


AmpScaler = GradScaler
