"""Automatic mixed precision.

Reference: python/paddle/amp/ (auto_cast.py amp_guard:383, grad_scaler.py,
amp_lists.py). TPU-native: bf16 is the native MXU input type, so the default
amp dtype is bfloat16 and loss scaling is a no-op for bf16 (its exponent
range equals fp32); the full dynamic-scaling machinery still exists for
fp16 parity.
"""
from .auto_cast import amp_guard, auto_cast, decorate, amp_decorate
from .grad_scaler import AmpScaler, GradScaler
from . import debugging

white_list = None
black_list = None


def is_float16_supported(device=None):
    """reference amp.is_float16_supported: XLA computes fp16 on every
    backend here (TPU prefers bf16 but supports fp16 compute)."""
    return True


def is_bfloat16_supported(device=None):
    """reference amp.is_bfloat16_supported: bf16 is the TPU-native
    compute dtype."""
    return True



