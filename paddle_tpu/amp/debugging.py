"""Numeric debugging (reference: python/paddle/amp/debugging.py —
TensorCheckerConfig:156, enable_operator_stats_collection)."""
from __future__ import annotations

import contextlib
from collections import Counter

from ..core import dispatch, flags
from ..core.tensor import Tensor


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


class TensorCheckerConfig:
    def __init__(self, enable=False, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None, skipped_op_list=None,
                 debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list
        self.debug_step = debug_step


def enable_tensor_checker(config: TensorCheckerConfig):
    if config.enable:
        level = 0 if config.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT else 1
        flags.set_flags({"check_nan_inf": True,
                         "check_nan_inf_level": level})


def disable_tensor_checker():
    flags.set_flags({"check_nan_inf": False})


_op_stats = Counter()
_collecting = False


def _stats_hook(op_name, inputs, outputs, attrs, duration=0.0):
    if _collecting:
        dt = outputs[0].dtype if outputs else None
        _op_stats[f"{op_name}:{dt}"] += 1


_hook_registered = False


@contextlib.contextmanager
def collect_operator_stats():
    global _collecting, _hook_registered
    if not _hook_registered:
        dispatch.register_op_hook(_stats_hook)
        _hook_registered = True
    _op_stats.clear()
    _collecting = True
    try:
        yield
    finally:
        _collecting = False
        print("<------------------------------ op list ------------------------------->")
        for key, cnt in sorted(_op_stats.items()):
            print(f"  {key}  calls={cnt}")


def enable_operator_stats_collection():
    global _collecting, _hook_registered
    if not _hook_registered:
        dispatch.register_op_hook(_stats_hook)
        _hook_registered = True
    _op_stats.clear()
    _collecting = True


def disable_operator_stats_collection():
    global _collecting
    _collecting = False
    for key, cnt in sorted(_op_stats.items()):
        print(f"  {key}  calls={cnt}")
