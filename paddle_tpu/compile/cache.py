"""Content-addressed on-disk compilation cache.

One entry per compiled program, named ``<sha256>.pcc``. The layout is a
fixed header carrying CRC32s for both the JSON meta block and the
payload, so torn writes and bit-rot are detected on read:

    magic ``PTPCC001`` | u32 meta_len | u32 meta_crc | u64 payload_len |
    u32 payload_crc | meta (JSON) | payload

Durability + concurrency contract (reuses the round-9 machinery):

- **Atomic publish** — entries are written to a same-directory temp file
  and published with :func:`framework.io.atomic_replace` (``os.replace``
  + directory fsync, ``io.rename_fail`` fault point honored). Concurrent
  writers of the same key are last-wins; both wrote identical content by
  construction (the key is content-addressed), so either winner is
  correct.
- **Quarantine, never crash** — a corrupt or torn entry is moved into
  ``quarantine/`` (atomic rename; unlinked if even that fails) and the
  lookup reports a miss, so the caller silently recompiles. Cache damage
  can cost time, never correctness.
- **LRU size budget** — ``FLAGS_compile_cache_size_mb`` bounds the entry
  bytes. Recency rides on entry mtimes (``get`` bumps them with one
  ``utime`` — no per-hit manifest rewrite, so fleet replicas sharing a
  directory don't clobber each other); the JSON manifest records
  publish-time metadata, is written once per ``put``, publishes
  atomically, and is advisory — missing or torn, everything still works
  from a directory scan.

Instrumented through ``observability``: ``paddle_tpu_pcc_hits_total`` /
``paddle_tpu_pcc_misses_total`` (labeled by call site), the
``paddle_tpu_pcc_bytes`` gauge, ``paddle_tpu_pcc_time_saved_seconds``,
and quarantine/eviction counters, with spans for lookup and publish.
"""
from __future__ import annotations

import json
import os
import struct
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..core import flags
from ..fault import inject as _inject
from ..observability import metrics as _metrics
from ..observability import trace as _trace

__all__ = ["CompileCache", "get_cache", "enabled", "cache_dir",
           "record_time_saved"]

_MAGIC = b"PTPCC001"
_HEADER = struct.Struct("<IIQI")   # meta_len, meta_crc, payload_len, payload_crc
_MANIFEST = "manifest.json"
_QUARANTINE = "quarantine"

# the compile_cache* flags are registered in core/flags.py so set_flags
# works before this package is first imported

_m_hits = _metrics.counter(
    "paddle_tpu_pcc_hits_total",
    "Persistent compilation cache hits (a compile skipped), labeled by "
    "call site: to_static, sot, artifact.", labelnames=("site",))
_m_misses = _metrics.counter(
    "paddle_tpu_pcc_misses_total",
    "Persistent compilation cache misses (entry absent, incompatible, or "
    "quarantined), labeled by call site.", labelnames=("site",))
_m_bytes = _metrics.gauge(
    "paddle_tpu_pcc_bytes",
    "Total bytes of live persistent compilation cache entries.")
_m_time_saved = _metrics.counter(
    "paddle_tpu_pcc_time_saved_seconds",
    "Cumulative compile wall time skipped by persistent cache hits (the "
    "miss-time compile cost recorded in each entry's meta).")
_m_quarantined = _metrics.counter(
    "paddle_tpu_pcc_quarantined_total",
    "Cache entries moved to quarantine after failing CRC/structure "
    "verification.", labelnames=("reason",))
_m_evicted = _metrics.counter(
    "paddle_tpu_pcc_evicted_total",
    "Cache entries evicted by the LRU size budget.")
_m_errors = _metrics.counter(
    "paddle_tpu_pcc_errors_total",
    "Cache operations abandoned on unexpected errors (the compile path "
    "continued without the cache).", labelnames=("op",))


def enabled() -> bool:
    return bool(flags.get_flag("compile_cache"))


def cache_dir() -> str:
    d = flags.get_flag("compile_cache_dir")
    if d:
        return os.path.expanduser(str(d))
    env = os.environ.get("PADDLE_TPU_COMPILE_CACHE_DIR")
    if env:
        return os.path.expanduser(env)
    return os.path.expanduser(os.path.join("~", ".cache", "paddle_tpu",
                                           "pcc"))


def record_time_saved(seconds: float) -> None:
    if seconds and seconds > 0:
        _m_time_saved.inc(float(seconds))


class CompileCache:
    """One cache directory. Cheap to construct; all state is on disk."""

    def __init__(self, directory: Optional[str] = None,
                 size_limit_mb: Optional[int] = None):
        self.directory = directory or cache_dir()
        self._size_limit_mb = size_limit_mb

    # ------------------------------------------------------------- layout
    def _path(self, key: str) -> str:
        return os.path.join(self.directory, f"{key}.pcc")

    def _manifest_path(self) -> str:
        return os.path.join(self.directory, _MANIFEST)

    def size_limit_bytes(self) -> int:
        mb = self._size_limit_mb
        if mb is None:
            mb = int(flags.get_flag("compile_cache_size_mb"))
        return max(int(mb), 1) * (1 << 20)

    # ------------------------------------------------------------ read
    def get(self, key: str, site: str = "other"
            ) -> Optional[Tuple[dict, bytes]]:
        """Return ``(meta, payload)`` or None. Verifies both CRCs; any
        damage quarantines the entry and reports a miss — a corrupt cache
        must cost a recompile, never a crash."""
        path = self._path(key)
        with _trace.span(f"pcc_lookup:{site}", "compile",
                         {"key": key[:12]}):
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                _m_misses.inc(site=site)
                return None
            entry = self._decode(data)
            if entry is None:
                self._quarantine(path, "corrupt")
                _m_misses.inc(site=site)
                return None
        # LRU touch: bump the entry's mtime (one utimensat) instead of
        # rewriting the manifest — a SOT-heavy startup does hundreds of
        # hits, and fleet replicas sharing a dir must not clobber each
        # other's bookkeeping per lookup
        try:
            os.utime(path)
        except OSError:
            pass
        _m_hits.inc(site=site)
        return entry

    def _decode(self, data: bytes) -> Optional[Tuple[dict, bytes]]:
        head = len(_MAGIC) + _HEADER.size
        if len(data) < head or data[:len(_MAGIC)] != _MAGIC:
            return None
        meta_len, meta_crc, payload_len, payload_crc = _HEADER.unpack(
            data[len(_MAGIC):head])
        if len(data) != head + meta_len + payload_len:
            return None
        meta_bytes = data[head:head + meta_len]
        payload = data[head + meta_len:]
        if zlib.crc32(meta_bytes) != meta_crc or \
                zlib.crc32(payload) != payload_crc:
            return None
        try:
            meta = json.loads(meta_bytes)
        except ValueError:
            return None
        if not isinstance(meta, dict):
            return None
        return meta, payload

    def _quarantine(self, path: str, reason: str) -> None:
        """Move a damaged entry aside (atomic) so it is never re-read;
        keep the bytes for post-mortems instead of deleting evidence."""
        _m_quarantined.inc(reason=reason)
        qdir = os.path.join(self.directory, _QUARANTINE)
        try:
            os.makedirs(qdir, exist_ok=True)
            dst = os.path.join(
                qdir, f"{os.path.basename(path)}.{os.getpid()}"
                f".{int(time.time() * 1e3)}")
            os.replace(path, dst)
        except OSError:
            try:
                os.unlink(path)
            except OSError:
                pass

    # ------------------------------------------------------------ write
    def put(self, key: str, payload: bytes, meta: dict) -> bool:
        """Atomically publish one entry, then enforce the LRU budget.
        Returns False (and leaves no partial file) on any failure — the
        caller already holds the compiled program, so a failed publish
        only costs the NEXT process a recompile."""
        from ..framework.io import atomic_replace

        meta = dict(meta)
        meta.setdefault("created", time.time())
        meta_bytes = json.dumps(meta, sort_keys=True).encode()
        blob = (_MAGIC
                + _HEADER.pack(len(meta_bytes), zlib.crc32(meta_bytes),
                               len(payload), zlib.crc32(payload))
                + meta_bytes + payload)
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}"
        with _trace.span("pcc_publish", "compile",
                         {"key": key[:12], "bytes": len(blob)}):
            try:
                os.makedirs(self.directory, exist_ok=True)
                with open(tmp, "wb") as f:
                    trunc = _inject.peek("pcc.write_truncate_after_bytes")
                    if trunc is not None:
                        keep = int(trunc.get("after_bytes", 0))
                        f.write(blob[:keep])
                        f.flush()
                        _inject.fire("pcc.write_truncate_after_bytes")
                        raise OSError(
                            f"injected truncation after {keep} bytes")
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                atomic_replace(tmp, path)
            except (OSError, ValueError):
                _m_errors.inc(op="put")
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return False
        self._record_put(key, len(blob))
        try:
            self.enforce_budget()
        except OSError:
            _m_errors.inc(op="evict")
        return True

    # --------------------------------------------------------- manifest
    def _read_manifest(self) -> Dict[str, dict]:
        try:
            with open(self._manifest_path()) as f:
                m = json.load(f)
            return m if isinstance(m, dict) else {}
        except (OSError, ValueError):
            return {}

    def _write_manifest(self, m: Dict[str, dict]) -> None:
        """Best-effort atomic rewrite; last-wins between processes. The
        manifest only steers LRU order — losing an update degrades
        eviction fairness, nothing else."""
        from ..framework.io import atomic_replace

        path = self._manifest_path()
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(self.directory, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(m, f)
            atomic_replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _record_put(self, key: str, nbytes: int) -> None:
        """Manifest bookkeeping, written once per publish (LRU recency
        itself rides on entry mtimes, bumped by ``get``)."""
        try:
            m = self._read_manifest()
            m[key] = {"bytes": int(nbytes), "created": time.time()}
            self._write_manifest(m)
        except Exception:
            _m_errors.inc(op="touch")

    # ---------------------------------------------------------- listing
    def entries(self) -> List[dict]:
        """Live entries, oldest-used first: [{key, bytes, used, path}].
        Recency comes from entry mtimes (``get`` bumps them), so the
        listing needs no manifest read and tolerates a torn one."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in sorted(names):
            if not name.endswith(".pcc"):
                continue
            key = name[:-len(".pcc")]
            path = os.path.join(self.directory, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append({"key": key, "bytes": st.st_size,
                        "used": float(st.st_mtime), "path": path})
        out.sort(key=lambda e: e["used"])
        total = sum(e["bytes"] for e in out)
        _m_bytes.set(float(total))
        return out

    def total_bytes(self) -> int:
        return sum(e["bytes"] for e in self.entries())

    def entry_meta(self, key: str) -> Optional[dict]:
        got = self._decode_file(self._path(key))
        return got[0] if got else None

    def _decode_file(self, path: str) -> Optional[Tuple[dict, bytes]]:
        try:
            with open(path, "rb") as f:
                return self._decode(f.read())
        except OSError:
            return None

    # --------------------------------------------------------- eviction
    def enforce_budget(self, limit_bytes: Optional[int] = None) -> int:
        """Evict least-recently-used entries past the budget; returns the
        number evicted. Safe under concurrency: eviction is unlink-based
        and a racing reader that loses simply recompiles."""
        limit = self.size_limit_bytes() if limit_bytes is None \
            else int(limit_bytes)
        live = self.entries()
        total = sum(e["bytes"] for e in live)
        evicted = 0
        manifest = None
        for e in live:
            if total <= limit:
                break
            try:
                os.unlink(e["path"])
            except OSError:
                continue
            total -= e["bytes"]
            evicted += 1
            _m_evicted.inc()
            if manifest is None:
                manifest = self._read_manifest()
            manifest.pop(e["key"], None)
        if manifest is not None:
            self._write_manifest(manifest)
        _m_bytes.set(float(max(total, 0)))
        return evicted

    def clear(self) -> int:
        """Drop every entry (and the manifest); returns entries removed."""
        n = 0
        for e in self.entries():
            try:
                os.unlink(e["path"])
                n += 1
            except OSError:
                pass
        try:
            os.unlink(self._manifest_path())
        except OSError:
            pass
        _m_bytes.set(0.0)
        return n


_singleton: Optional[CompileCache] = None


def get_cache() -> CompileCache:
    """Process-wide cache bound to the flag-configured directory (a new
    object is handed out if the directory flag changed — tests repoint
    the cache at tmp dirs)."""
    global _singleton
    target = cache_dir()
    if _singleton is None or _singleton.directory != target:
        _singleton = CompileCache(target)
    return _singleton
