"""paddle_tpu.compile — persistent compilation cache + AOT warmup.

Every process that traces, lowers, and XLA-compiles the same program is
wasting the fleet's time: compile wall time dominates cold start (the
round-8 profiler numbers), and the work is identical across replicas.
This package amortizes it (cf. JAX/XLA AOT export and Pathways' fleet-
wide compilation reuse):

- :mod:`.cache` — content-addressed on-disk entries, CRC-verified,
  atomically published, LRU-bounded by ``FLAGS_compile_cache_size_mb``;
  corrupt entries are quarantined and silently recompiled.
- :mod:`.aot` — two entry tiers: serialized PjRt executables (hit skips
  trace+lower+XLA compile) with a serialized-StableHLO fallback where
  executable serialization is unavailable (hit still skips trace+lower).
- :mod:`.fingerprint` — keys over program content + jax/jaxlib versions
  + backend/topology + lowering FLAGS.
- :mod:`.warmup` — shape-signature manifest recording plus
  ``python -m paddle_tpu.compile warm <manifest>`` to precompile every
  recorded signature before traffic arrives.

Wired into the three compile paths: ``jit.to_static`` dispatch, SOT
segment flushes, and loaded inference artifacts (``jit.load`` /
``inference.Predictor``). Enable with ``FLAGS_compile_cache=1`` (cache
directory: ``FLAGS_compile_cache_dir`` or
``$PADDLE_TPU_COMPILE_CACHE_DIR``).
"""
from __future__ import annotations

from .cache import (CompileCache, cache_dir, enabled, get_cache,
                    record_time_saved)
from .fingerprint import (aval_sig, blob_digest, code_fingerprint,
                          env_fingerprint, key_of)
from .warmup import (manifest_path, read_manifest, record_artifact,
                     record_to_static, warm)
from . import aot

__all__ = [
    "CompileCache", "get_cache", "enabled", "cache_dir",
    "record_time_saved", "key_of", "env_fingerprint", "aval_sig",
    "blob_digest", "code_fingerprint", "warm", "record_to_static",
    "record_artifact",
    "manifest_path", "read_manifest", "aot",
]
