"""CLI for the persistent compilation cache.

    python -m paddle_tpu.compile warm <manifest.jsonl>   precompile all
        recorded signatures into the cache (run before traffic arrives)
    python -m paddle_tpu.compile inspect                 list entries
    python -m paddle_tpu.compile prune [--max-mb N]      enforce budget
    python -m paddle_tpu.compile clear                   drop everything

Exit status: 0 on success; ``warm`` exits 1 when every record failed
(a fleet bootstrap that warmed nothing should fail loudly).
"""
from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.compile",
        description="persistent compilation cache tools")
    sub = ap.add_subparsers(dest="cmd", required=True)
    ap_warm = sub.add_parser("warm", help="precompile a shape-signature "
                             "manifest into the cache")
    ap_warm.add_argument("manifest")
    ap_warm.add_argument("--cache-dir", default="",
                         help="override FLAGS_compile_cache_dir")
    sub.add_parser("inspect", help="list cache entries")
    ap_prune = sub.add_parser("prune", help="enforce the LRU size budget")
    ap_prune.add_argument("--max-mb", type=int, default=None,
                          help="budget override (default: "
                          "FLAGS_compile_cache_size_mb)")
    sub.add_parser("clear", help="remove every cache entry")
    args = ap.parse_args(argv)

    from paddle_tpu.core import flags
    if getattr(args, "cache_dir", ""):
        flags.set_flags({"FLAGS_compile_cache_dir": args.cache_dir})
    from paddle_tpu import compile as pcc

    cache = pcc.get_cache()
    if args.cmd == "warm":
        flags.set_flags({"FLAGS_compile_cache": True})
        summary = pcc.warm(args.manifest)
        print(json.dumps(summary, indent=2))
        return 0 if (summary["warmed"] or not summary["failed"]) else 1
    if args.cmd == "inspect":
        entries = cache.entries()
        total = sum(e["bytes"] for e in entries)
        for e in entries:
            meta = cache.entry_meta(e["key"]) or {}
            print(f"{e['key'][:16]}  {e['bytes']:>10d} B  "
                  f"used {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(e['used']))}  "
                  f"site={meta.get('site', '?')} tier={meta.get('tier', '?')}  "
                  f"{meta.get('label', '')}")
        print(f"{len(entries)} entries, {total / (1 << 20):.2f} MB "
              f"(budget {cache.size_limit_bytes() / (1 << 20):.0f} MB) "
              f"in {cache.directory}")
        return 0
    if args.cmd == "prune":
        limit = None if args.max_mb is None else args.max_mb * (1 << 20)
        n = cache.enforce_budget(limit)
        print(f"evicted {n} entries; "
              f"{cache.total_bytes() / (1 << 20):.2f} MB live")
        return 0
    if args.cmd == "clear":
        n = cache.clear()
        print(f"removed {n} entries from {cache.directory}")
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
