"""AOT serialization helpers — two tiers of reusable compiled programs.

Tier ``exec``: the PjRt executable itself, via
``jax.experimental.serialize_executable``. A hit skips trace, lower, AND
the XLA compile — the program starts running immediately (this is what
makes a warmed serving replica's first request a cache hit).

Tier ``stablehlo``: the ``jax.export`` serialization of the lowered
program. Used where the backend cannot serialize executables — a hit
still skips Python trace + StableHLO lowering and pays only the XLA
compile of the stored module.

Both deserialize paths are deliberately forgiving: version skew, platform
mismatch, or any other incompatibility returns ``None`` (a miss → the
caller recompiles). The CRC layer in :mod:`.cache` already filtered out
corruption, so failures here mean "not usable on this runtime", which is
a legitimate miss, not an error.

Donation: a serialized ``exec``-tier executable carries its input→output
buffer aliasing, so a deserialized donated program donates exactly like
the locally-compiled one — callers record ``donate`` in the entry meta
and fold it into the cache key (``to_static._pcc_key``) so donated and
undonated programs can never cross-hit; the ``stablehlo`` tier drops
aliasing on export (a hit is correct but pays the undonated memory).
"""
from __future__ import annotations

import pickle
from typing import Callable, Optional, Tuple

from ..observability import metrics as _metrics
from ..observability import trace as _trace

__all__ = ["serialize_compiled", "serialize_exported", "load_runner",
           "TIER_EXEC", "TIER_STABLEHLO"]

TIER_EXEC = "exec"
TIER_STABLEHLO = "stablehlo"

_m_deser_fail = _metrics.counter(
    "paddle_tpu_pcc_deserialize_incompatible_total",
    "Cache entries that decoded cleanly but could not be loaded on this "
    "runtime (version/platform skew) — treated as misses.",
    labelnames=("tier",))


def serialize_compiled(compiled) -> Optional[Tuple[str, bytes]]:
    """Serialize a ``jax.stages.Compiled``; None when the backend cannot
    (the caller falls back to :func:`serialize_exported`)."""
    try:
        from jax.experimental import serialize_executable as se
        payload, in_tree, out_tree = se.serialize(compiled)
        return TIER_EXEC, pickle.dumps((payload, in_tree, out_tree),
                                       protocol=4)
    except Exception:
        return None


def serialize_exported(exported) -> Optional[Tuple[str, bytes]]:
    """Serialize a ``jax.export.Exported`` StableHLO program."""
    try:
        return TIER_STABLEHLO, bytes(exported.serialize())
    except Exception:
        return None


def load_runner(tier: str, payload: bytes) -> Optional[Callable]:
    """Rebuild a callable from a cache payload; None = unusable here.

    The returned callable takes exactly the dynamic (non-static)
    arguments the original function was compiled for.
    """
    if tier == TIER_EXEC:
        try:
            from jax.experimental import serialize_executable as se
            with _trace.span("pcc_deserialize:exec", "compile"):
                blob, in_tree, out_tree = pickle.loads(payload)
                return se.deserialize_and_load(blob, in_tree, out_tree)
        except Exception:
            _m_deser_fail.inc(tier=TIER_EXEC)
            return None
    if tier == TIER_STABLEHLO:
        try:
            from jax import export as jax_export
            with _trace.span("pcc_deserialize:stablehlo", "compile"):
                exported = jax_export.deserialize(payload)
            return exported.call
        except Exception:
            _m_deser_fail.inc(tier=TIER_STABLEHLO)
            return None
    _m_deser_fail.inc(tier=tier or "unknown")
    return None
