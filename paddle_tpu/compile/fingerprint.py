"""Cache-key fingerprints for the persistent compilation cache.

A cache entry is only reusable when EVERYTHING that shaped the compiled
program is identical: the program content (a function fingerprint + the
dispatch signature for ``to_static``/SOT, the serialized-StableHLO digest
for saved artifacts), the toolchain (jax/jaxlib versions), the target
(backend platform, device kind, device count — a v5e executable must
never be fed to a v4, nor a 1-chip program to an 8-chip mesh), the
compile options, and the FLAGS that alter lowering (matmul precision,
Pallas kernel selection, flash-attention thresholds). All of it is folded
into one hex sha256; two processes on identical machines derive identical
keys, which is what makes the cache shareable across a serving fleet
(cf. the Pathways emphasis on amortizing compilation fleet-wide).
"""
from __future__ import annotations

import hashlib
import json
import types
from typing import Any, Dict, Sequence

import jax

from ..core import flags

#: flags that change what XLA receives — part of every cache key. Keep in
#: sync with the lowering sites that read them.
LOWERING_FLAGS = (
    "tpu_matmul_precision",
    "use_pallas_kernels",
    "flash_min_seq_len",
    "cudnn_deterministic",
)

_env_cache: Dict[str, Any] = {}


def env_fingerprint() -> Dict[str, Any]:
    """The toolchain + topology part of every key (computed once — none
    of it can change inside a process). Includes the framework's own
    version so upgrading paddle_tpu (whose op lowerings feed every
    program) invalidates entries wholesale."""
    if not _env_cache:
        import jaxlib

        try:
            from .. import __version__ as fw_version
        except ImportError:
            fw_version = "?"
        devices = jax.devices()
        _env_cache.update({
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "paddle_tpu": str(fw_version),
            "platform": devices[0].platform,
            "device_kind": getattr(devices[0], "device_kind", ""),
            "device_count": jax.device_count(),
        })
    out = dict(_env_cache)
    out["flags"] = {name: flags.get_flag(name) for name in LOWERING_FLAGS}
    return out


def code_fingerprint(fn) -> str:
    """Content hash of a function's code object — bytecode, names, and
    constants, recursing into nested code objects. File/line-based
    fingerprints stale-hit when a body is edited in place; the
    persistent cache must key on what the function DOES. (Callables the
    entry function merely calls are not folded in — the entry hash plus
    closure guards plus the framework version in :func:`env_fingerprint`
    cover the common edit paths; clear the cache after deeper surgery.)
    """
    code = getattr(fn, "__code__", None)
    if code is None:
        call = getattr(fn, "__call__", None)
        code = getattr(call, "__code__", None)
    if code is None:
        return f"<no-code:{type(fn).__name__}>"
    h = hashlib.sha256()

    def fold(c):
        h.update(c.co_code)
        h.update(repr(c.co_names).encode())
        h.update(repr(c.co_varnames).encode())
        for const in c.co_consts:
            if isinstance(const, types.CodeType):
                fold(const)
            else:
                h.update(repr(const).encode())

    fold(code)
    return h.hexdigest()


def _canon(obj) -> str:
    """Deterministic string form of a key part (sorted-key JSON when
    possible, repr otherwise — reprs here are stable strings built by the
    callers, never raw object reprs with addresses)."""
    try:
        return json.dumps(obj, sort_keys=True, default=repr)
    except (TypeError, ValueError):
        return repr(obj)


def key_of(kind: str, *parts) -> str:
    """Hex sha256 over (kind, env, parts) — the entry's file name."""
    h = hashlib.sha256()
    h.update(kind.encode())
    h.update(_canon(env_fingerprint()).encode())
    for p in parts:
        h.update(b"\x00")
        h.update(_canon(p).encode())
    return h.hexdigest()


def aval_sig(arrays: Sequence) -> list:
    """JSON-able [[shape, dtype], ...] for arrays / ShapeDtypeStructs."""
    return [[list(getattr(a, "shape", ())), str(a.dtype)] for a in arrays]


def blob_digest(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()
