"""AOT warmup — record shape signatures during a run, precompile later.

A serving replica should never pay trace+lower+compile on its first
request. The flow:

1. A recording run (CI, a canary, a previous replica) sets
   ``FLAGS_compile_cache_manifest=/path/sigs.jsonl``; every ``to_static``
   signature and every loaded-artifact call appends one JSON line
   describing *what was compiled* (import target or artifact path +
   input shapes/dtypes).
2. ``python -m paddle_tpu.compile warm sigs.jsonl`` (or
   :func:`warm`) replays the manifest with abstract values only — no
   data, no device traffic — publishing every compiled program into the
   persistent cache.
3. The replica starts with ``FLAGS_compile_cache=1``; its first dispatch
   of every recorded signature is a cache hit.

Records whose target cannot be re-imported (lambdas, closures, bound
methods of ad-hoc objects) are recorded with ``"target": null`` and
reported as skipped by ``warm`` — the manifest is an honest inventory,
not a promise.
"""
from __future__ import annotations

import importlib
import json
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence

from ..core import flags

__all__ = ["record_to_static", "record_artifact", "warm",
           "manifest_path", "read_manifest"]

# FLAGS_compile_cache_manifest is registered in core/flags.py

_lock = threading.Lock()
_written: set = set()


def manifest_path() -> str:
    return str(flags.get_flag("compile_cache_manifest") or "")


def _append(record: dict) -> None:
    path = manifest_path()
    if not path:
        return
    line = json.dumps(record, sort_keys=True)
    with _lock:
        if (path, line) in _written:
            return
        _written.add((path, line))
        try:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(path, "a") as f:
                f.write(line + "\n")
        except OSError:
            pass


def _import_target(fn) -> Optional[str]:
    """``module:qualname`` when ``fn`` is faithfully re-importable, else
    None. Dotted qualnames (staticmethods, class-attribute functions)
    resolve fine via the attribute walk in ``_resolve``; bound methods
    do NOT — re-importing yields the bare function without the instance
    whose parameters keyed the original compile — nor do closures."""
    mod = getattr(fn, "__module__", None)
    qual = getattr(fn, "__qualname__", None)
    if not mod or not qual or "<locals>" in qual:
        return None
    if getattr(fn, "__self__", None) is not None:
        return None
    return f"{mod}:{qual}"


def record_to_static(fn, arrays: Sequence) -> None:
    """Record one to_static dispatch signature (cheap no-op when the
    manifest flag is unset)."""
    if not manifest_path():
        return
    from .fingerprint import aval_sig
    _append({"kind": "to_static", "target": _import_target(fn),
             "name": getattr(fn, "__qualname__", str(fn)),
             "arrays": aval_sig(arrays)})


def record_artifact(path: str, arrays: Sequence) -> None:
    """Record one loaded-artifact (TranslatedLayer / Predictor) call."""
    if not manifest_path():
        return
    from .fingerprint import aval_sig
    _append({"kind": "artifact", "path": str(path),
             "arrays": aval_sig(arrays)})


def read_manifest(path: str) -> List[dict]:
    out, seen = [], set()
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line in seen:
                continue
            seen.add(line)
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _avals(sig: Sequence) -> list:
    import jax
    import jax.numpy as jnp
    import numpy as np

    out = []
    for shape, dtype in sig:
        dt = jnp.bfloat16 if str(dtype) == "bfloat16" else np.dtype(dtype)
        out.append(jax.ShapeDtypeStruct(tuple(shape), dt))
    return out


def warm(manifest: str,
         resolver: Optional[Callable[[dict], Optional[object]]] = None
         ) -> Dict[str, list]:
    """Precompile every signature in ``manifest`` into the persistent
    cache. ``resolver`` may map a record to a callable/Layer for targets
    the default import logic cannot reach. Returns
    ``{"warmed": [...], "skipped": [...], "failed": [...]}`` — warming is
    best-effort by design: a record that no longer resolves must not
    block the rest of the fleet's warmup."""
    from ..jit import api as jit_api

    summary: Dict[str, list] = {"warmed": [], "skipped": [], "failed": []}
    for rec in read_manifest(manifest):
        label = rec.get("target") or rec.get("path") or rec.get("name", "?")
        try:
            avals = _avals(rec.get("arrays", []))
            target = resolver(rec) if resolver is not None else None
            if target is None:
                target = _resolve(rec, jit_api)
            if target is None:
                summary["skipped"].append(label)
                continue
            if not isinstance(target, (jit_api.StaticFunction,
                                       jit_api.TranslatedLayer)):
                target = jit_api.to_static(target, full_graph=True)
            target.precompile(avals)
            summary["warmed"].append(label)
        except Exception as e:
            summary["failed"].append(f"{label}: {type(e).__name__}: {e}")
    return summary


def _resolve(rec: dict, jit_api):
    kind = rec.get("kind")
    if kind == "artifact":
        loaded = jit_api.load(rec["path"])
        return loaded if isinstance(loaded, jit_api.TranslatedLayer) \
            else None
    if kind == "to_static":
        target = rec.get("target")
        if not target or ":" not in target:
            return None
        mod_name, attr = target.split(":", 1)
        obj = importlib.import_module(mod_name)
        for part in attr.split("."):
            obj = getattr(obj, part)
        return obj
    return None
