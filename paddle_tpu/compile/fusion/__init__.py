"""Graph-fusion pass over op-list IRs.

The optimization layer the round-12 perf stack measures against
(reference: CINN fusion/codegen + the 71-entry ``fused_ops.yaml`` hot
set). One pattern-matching core rewrites matched subgraphs onto the
first-class fused ops of :mod:`paddle_tpu.nn.functional.fused`, and
three thin adapters wire it into every compile path:

* ``fuse_steps``           — the core: match + external-use-checked
  rewrite over any op list whose records carry
  ``name/fn/in_ids/out_ids/attrs/in_shapes/out_shapes``
  (``static.Program``'s ``_OpRecord`` natively qualifies).
* ``fuse_program_ops``     — ``static.Program`` / ``Executor.run``.
* ``trace_rewrite``        — ``to_static`` / ``Engine``: captures the
  dispatched op stream during the trace, then re-emits the fused
  subgraphs THROUGH the dispatcher (so spmd propagation, cost
  accounting, and metrics all see the fused ops) and swaps the new
  values into the function's outputs; the superseded unfused ops die
  in XLA DCE.
* ``fuse_sot_nodes``       — SOT segment flush: the pending segment
  graph is rewritten before its ``seg_fn`` compiles.

Patterns (the inventory README documents):

=================  ======================================================
``norm_linear``    layer_norm/rms_norm → linear[→ gelu/silu]   (one GEMM
                   with norm prologue + bias/act epilogue)
``linear_act``     linear → gelu/silu                (norm-less variant)
``residual_norm``  add(x, y) → layer_norm/rms_norm   (sum stays a REAL
                   output, so external residual-stream uses are legal)
``bias_act``       add(x, bias-vector) → gelu/silu/relu
``rope_proj``      linear → reshape(B,S,H,D) → rotary_embedding
=================  ======================================================

Rejection rule: an *interior* value (consumed by the fused op and not
re-emitted as one of its outputs) that is externally visible — fetched,
returned, or read by any step outside the chain — rejects the match
(counted in ``paddle_tpu_fusion_rejected_total{pattern=}``).

Everything is gated by ``FLAGS_enable_fusion`` (default off: the seed
behavior is bit-exact) and fingerprinted into the persistent-compile
cache keys so fused and unfused programs can never cross-hit.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ...core import flags
from ...observability import metrics as _metrics

__all__ = ["enabled", "fingerprint", "fuse_steps", "fuse_program_ops",
           "trace_rewrite", "fuse_sot_nodes", "FusedStep", "PATTERNS",
           "FUSION_VERSION"]

#: bump when the pattern set or a fused rewrite's semantics change —
#: folded into every compile-cache key so stale fused programs die
FUSION_VERSION = 1

PATTERNS = ("norm_linear", "linear_act", "residual_norm", "bias_act",
            "rope_proj")

_NORM_OPS = ("layer_norm", "rms_norm")
_ACT_OPS = ("gelu", "silu", "relu")

_m_matched = _metrics.counter(
    "paddle_tpu_fusion_matched_total",
    "Fusion-pattern candidates that matched structurally (rewritten + "
    "rejected).", labelnames=("pattern",))
_m_rewritten = _metrics.counter(
    "paddle_tpu_fusion_rewritten_total",
    "Fusion-pattern candidates rewritten onto fused ops.",
    labelnames=("pattern",))
_m_rejected = _metrics.counter(
    "paddle_tpu_fusion_rejected_total",
    "Fusion-pattern candidates rejected (interior value externally "
    "visible, multi-consumer interior, or producer-order hazard).",
    labelnames=("pattern",))


def enabled() -> bool:
    return bool(flags.get_flag("enable_fusion"))


def fingerprint() -> str:
    """Cache-key component describing the rewrite the pass would apply
    (folded into pcc keys + jit statics so fused/unfused programs and
    different pattern vintages never share a compiled entry)."""
    return f"fusion/v{FUSION_VERSION}[{','.join(PATTERNS)}]"


@dataclass
class FusedStep:
    """One rewritten subgraph, replayable like an ``_OpRecord``."""

    name: str
    fn: Callable
    in_ids: tuple
    out_ids: tuple
    attrs: dict = field(default_factory=dict)
    in_shapes: tuple = ()
    out_shapes: tuple = ()
    pattern: str = ""
    #: AMP state active when the anchor op was recorded (trace_rewrite
    #: replays under it so fused GEMMs keep the bf16 discipline the
    #: unfused chain had; None = replay under the ambient state)
    amp: Optional[tuple] = None
    #: source provenance of the anchor record ("file.py:123") — carried
    #: through the rewrite so program-verifier findings on a fused op
    #: still name the user line that produced the chain
    loc: str = ""


def _act_name(step) -> Optional[str]:
    """Map a matched activation step to the fused epilogue vocabulary."""
    if step.name == "gelu":
        return "gelu_tanh" if (step.attrs or {}).get("approximate") \
            else "gelu"
    if step.name in ("silu", "relu"):
        return step.name
    return None


class _Graph:
    """Def/use index over the step list."""

    def __init__(self, steps, external_ids):
        self.steps = list(steps)
        self.external = set(external_ids)
        self.producer: Dict = {}
        self.uses: Dict = {}
        for i, st in enumerate(self.steps):
            for o in st.out_ids:
                self.producer[o] = i
            for v in st.in_ids:
                self.uses.setdefault(v, []).append(i)

    def sole_consumer(self, vid) -> Optional[int]:
        u = self.uses.get(vid, [])
        return u[0] if len(u) == 1 else None

    def interior_ok(self, vid, consumer_idx) -> bool:
        """vid may be swallowed: exactly one consumer and not external."""
        return (self.sole_consumer(vid) == consumer_idx
                and vid not in self.external)

    def inputs_available(self, in_ids, first_idx) -> bool:
        """Every fused-step input must exist before the fused step's
        position (graph inputs always do; produced values must come
        from earlier steps)."""
        return all(self.producer.get(v, -1) < first_idx for v in in_ids)


# --------------------------------------------------------------------------
# Pattern matchers: (graph, i) -> (match | None, rejected: bool)
# match = (pattern, consumed_indices, FusedStep)
# --------------------------------------------------------------------------
def _lazy_fused():
    from ...nn.functional import fused as FF
    return FF


def _match_norm_linear(g: _Graph, i: int):
    st = g.steps[i]
    if st.name not in _NORM_OPS:
        return None, False
    attrs = st.attrs or {}
    if attrs.get("norm_ndim") != 1 or "epsilon" not in attrs:
        return None, False          # pre-attr record or multi-dim norm
    y = st.out_ids[0]
    consumers = g.uses.get(y, [])
    lin_idx = next((j for j in consumers if g.steps[j].name == "linear"
                    and g.steps[j].in_ids
                    and g.steps[j].in_ids[0] == y), None)
    if lin_idx is None:
        return None, False
    # structural candidate exists from here on
    if not g.interior_ok(y, lin_idx):
        return "rejected", True
    lin = g.steps[lin_idx]
    has_bias = len(lin.in_ids) == 3
    consumed = [i, lin_idx]
    act = ""
    out_step = lin
    lin_out = lin.out_ids[0]
    act_idx = g.sole_consumer(lin_out)
    if (act_idx is not None and g.steps[act_idx].name in _ACT_OPS
            and lin_out not in g.external):
        a = _act_name(g.steps[act_idx])
        if a is not None:
            act = a
            consumed.append(act_idx)
            out_step = g.steps[act_idx]
    has_w = bool(attrs.get("has_w", len(st.in_ids) >= 2))
    has_b = bool(attrs.get("has_b", len(st.in_ids) >= 3))
    nw = st.in_ids[1] if has_w else None
    nb = st.in_ids[1 + has_w] if has_b else None
    in_ids = [st.in_ids[0], lin.in_ids[1]]
    in_shapes = [st.in_shapes[0], lin.in_shapes[1]]
    if has_bias:
        in_ids.append(lin.in_ids[2])
        in_shapes.append(lin.in_shapes[2])
    if nw is not None:
        in_ids.append(nw)
        in_shapes.append(st.in_shapes[1])
    if nb is not None:
        in_ids.append(nb)
        in_shapes.append(st.in_shapes[1 + has_w])
    if not g.inputs_available(in_ids, i):
        return "rejected", True
    FF = _lazy_fused()
    fused = FusedStep(
        name="fused_norm_linear",
        fn=FF.norm_linear_lowering(st.name, float(attrs["epsilon"]),
                                   act, has_bias, has_w, has_b),
        in_ids=tuple(in_ids), out_ids=tuple(out_step.out_ids),
        attrs={"norm_type": st.name, "epsilon": float(attrs["epsilon"]),
               "activation": act},
        in_shapes=tuple(in_shapes), out_shapes=tuple(out_step.out_shapes),
        pattern="norm_linear")
    return ("norm_linear", consumed, fused), False


def _match_linear_act(g: _Graph, i: int):
    st = g.steps[i]
    if st.name != "linear" or not st.out_ids:
        return None, False
    lin_out = st.out_ids[0]
    act_idx = g.sole_consumer(lin_out)
    consumers = g.uses.get(lin_out, [])
    has_act_consumer = any(g.steps[j].name in _ACT_OPS
                           and _act_name(g.steps[j]) is not None
                           for j in consumers)
    if not has_act_consumer:
        return None, False
    if act_idx is None or lin_out in g.external:
        return "rejected", True
    act = _act_name(g.steps[act_idx])
    if act is None:
        return None, False
    has_bias = len(st.in_ids) == 3
    if not g.inputs_available(st.in_ids, i):
        return "rejected", True
    FF = _lazy_fused()
    fused = FusedStep(
        name="fused_norm_linear",
        fn=FF.norm_linear_lowering("", 0.0, act, has_bias, False,
                                   False),
        in_ids=tuple(st.in_ids), out_ids=tuple(g.steps[act_idx].out_ids),
        attrs={"norm_type": "", "activation": act},
        in_shapes=tuple(st.in_shapes),
        out_shapes=tuple(g.steps[act_idx].out_shapes),
        pattern="linear_act")
    return ("linear_act", [i, act_idx], fused), False


def _match_residual_norm(g: _Graph, i: int):
    st = g.steps[i]
    if st.name != "add" or len(st.in_ids) != 2 or not st.out_ids:
        return None, False
    if (len(st.in_shapes) != 2 or st.in_shapes[0] != st.in_shapes[1]
            or len(st.in_shapes[0]) < 2
            or st.in_shapes[0] != st.out_shapes[0]):
        return None, False           # not a same-shape residual add
    s_out = st.out_ids[0]
    norm_idx = next(
        (j for j in g.uses.get(s_out, [])
         if g.steps[j].name in _NORM_OPS
         and (g.steps[j].attrs or {}).get("norm_ndim") == 1
         and "epsilon" in (g.steps[j].attrs or {})
         and g.steps[j].in_ids and g.steps[j].in_ids[0] == s_out), None)
    if norm_idx is None:
        return None, False
    norm = g.steps[norm_idx]
    attrs = norm.attrs or {}
    has_w = bool(attrs.get("has_w", len(norm.in_ids) >= 2))
    has_b = bool(attrs.get("has_b", len(norm.in_ids) >= 3))
    in_ids = list(st.in_ids) + list(norm.in_ids[1:])
    in_shapes = list(st.in_shapes) + list(norm.in_shapes[1:])
    if not g.inputs_available(in_ids, i):
        return "rejected", True
    # the sum is RE-EMITTED as the fused op's second output, so other
    # consumers / external visibility of it are legal — only the norm
    # output is interior-free by construction (it IS an output too)
    FF = _lazy_fused()
    fused = FusedStep(
        name="fused_residual_norm",
        fn=FF.residual_norm_lowering(norm.name,
                                     float(attrs["epsilon"]), has_w,
                                     has_b),
        in_ids=tuple(in_ids),
        out_ids=(norm.out_ids[0], s_out),
        attrs={"norm_type": norm.name,
               "epsilon": float(attrs["epsilon"])},
        in_shapes=tuple(in_shapes),
        out_shapes=(norm.out_shapes[0], st.out_shapes[0]),
        pattern="residual_norm")
    return ("residual_norm", [i, norm_idx], fused), False


def _match_bias_act(g: _Graph, i: int):
    st = g.steps[i]
    if st.name != "add" or len(st.in_ids) != 2 or not st.out_ids:
        return None, False
    shapes = list(st.in_shapes) if len(st.in_shapes) == 2 else None
    if shapes is None:
        return None, False
    out_shape = st.out_shapes[0] if st.out_shapes else ()
    bias_side = None
    for side in (1, 0):
        other = 1 - side
        if (len(shapes[side]) == 1 and len(shapes[other]) >= 2
                and len(out_shape) >= 1
                and int(shapes[side][0]) == int(out_shape[-1])):
            bias_side = side
            break
    if bias_side is None:
        return None, False
    add_out = st.out_ids[0]
    consumers = g.uses.get(add_out, [])
    if not any(g.steps[j].name in _ACT_OPS
               and _act_name(g.steps[j]) is not None
               for j in consumers):
        return None, False
    act_idx = g.sole_consumer(add_out)
    if act_idx is None or add_out in g.external:
        return "rejected", True
    act = _act_name(g.steps[act_idx])
    if act is None:
        return None, False
    x_side = 1 - bias_side
    in_ids = (st.in_ids[x_side], st.in_ids[bias_side])
    if not g.inputs_available(in_ids, i):
        return "rejected", True
    FF = _lazy_fused()
    fused = FusedStep(
        name="fused_bias_act",
        fn=FF.bias_act_lowering(act),
        in_ids=in_ids, out_ids=tuple(g.steps[act_idx].out_ids),
        attrs={"activation": act},
        in_shapes=(st.in_shapes[x_side], st.in_shapes[bias_side]),
        out_shapes=tuple(g.steps[act_idx].out_shapes),
        pattern="bias_act")
    return ("bias_act", [i, act_idx], fused), False


def _match_rope_proj(g: _Graph, i: int):
    st = g.steps[i]
    if st.name != "linear" or not st.out_ids:
        return None, False
    if len(st.in_shapes) < 2 or len(st.in_shapes[0]) != 3:
        return None, False
    lin_out = st.out_ids[0]
    rs_idx = g.sole_consumer(lin_out)
    if rs_idx is None or g.steps[rs_idx].name != "reshape":
        return None, False
    rs = g.steps[rs_idx]
    if not rs.out_shapes or len(rs.out_shapes[0]) != 4:
        return None, False
    b, s, h, d = (int(v) for v in rs.out_shapes[0])
    if (b, s) != tuple(int(v) for v in st.in_shapes[0][:2]) \
            or h * d != int(st.out_shapes[0][-1]):
        return None, False
    rope_idx = g.sole_consumer(rs.out_ids[0])
    if rope_idx is None \
            or g.steps[rope_idx].name != "rotary_embedding":
        return None, False
    rope = g.steps[rope_idx]
    attrs = rope.attrs or {}
    if "theta" not in attrs or "pos_offset" not in attrs:
        return None, False           # traced offset: stays unfused
    # candidate exists: interior values are the projection + reshape
    if lin_out in g.external or rs.out_ids[0] in g.external:
        return "rejected", True
    has_bias = len(st.in_ids) == 3
    if not g.inputs_available(st.in_ids, i):
        return "rejected", True
    FF = _lazy_fused()
    fused = FusedStep(
        name="fused_rope_proj",
        fn=FF.rope_proj_lowering(h, float(attrs["theta"]),
                                 int(attrs["pos_offset"]), has_bias),
        in_ids=tuple(st.in_ids), out_ids=tuple(rope.out_ids),
        attrs={"num_heads": h, "theta": float(attrs["theta"]),
               "pos_offset": int(attrs["pos_offset"])},
        in_shapes=tuple(st.in_shapes),
        out_shapes=tuple(rope.out_shapes),
        pattern="rope_proj")
    return ("rope_proj", [i, rs_idx, rope_idx], fused), False


#: attempt order at each step index: most-specific first
_MATCHERS = (_match_rope_proj, _match_norm_linear, _match_residual_norm,
             _match_bias_act, _match_linear_act)


# --------------------------------------------------------------------------
# The pass
# --------------------------------------------------------------------------
def fuse_steps(steps: Sequence, external_ids) -> Tuple[list, dict]:
    """Rewrite matched subgraphs; returns ``(plan, stats)``.

    ``plan`` preserves program order: unmatched records pass through
    untouched (same objects), each matched chain is replaced by ONE
    :class:`FusedStep` at the chain head's position. ``external_ids``
    are value ids visible outside the op list (fetches / returns);
    interior values reaching them reject the match.
    """
    g = _Graph(steps, external_ids)
    stats = {"ops_before": len(g.steps), "matched": {}, "rewritten": {},
             "rejected": {}, "patterns": {}}
    consumed = set()
    replacement: Dict[int, FusedStep] = {}
    metered = _metrics.enabled()
    for i in range(len(g.steps)):
        if i in consumed:
            continue
        for matcher in _MATCHERS:
            res, rejected = matcher(g, i)
            if rejected:
                pattern = matcher.__name__.replace("_match_", "")
                stats["matched"][pattern] = \
                    stats["matched"].get(pattern, 0) + 1
                stats["rejected"][pattern] = \
                    stats["rejected"].get(pattern, 0) + 1
                if metered:
                    _m_matched.inc(pattern=pattern)
                    _m_rejected.inc(pattern=pattern)
                continue
            if res is None:
                continue
            pattern, idxs, fused = res
            if any(j in consumed for j in idxs):
                continue
            stats["matched"][pattern] = \
                stats["matched"].get(pattern, 0) + 1
            stats["rewritten"][pattern] = \
                stats["rewritten"].get(pattern, 0) + 1
            if metered:
                _m_matched.inc(pattern=pattern)
                _m_rewritten.inc(pattern=pattern)
            consumed.update(idxs)
            fused.amp = getattr(g.steps[i], "amp", None)
            fused.loc = getattr(g.steps[i], "loc", "") or ""
            replacement[i] = fused
            break
    plan: List = []
    for i, st in enumerate(g.steps):
        if i in replacement:
            plan.append(replacement[i])
        elif i not in consumed:
            plan.append(st)
    stats["ops_after"] = len(plan)
    stats["patterns"] = dict(stats["rewritten"])
    return plan, stats


def fuse_program_ops(ops_list, fetch_ids) -> Tuple[list, dict]:
    """``static.Program`` adapter: ``_OpRecord`` list in, replayable
    plan out (fetched value ids are the external set)."""
    return fuse_steps(ops_list, set(fetch_ids))


# --------------------------------------------------------------------------
# to_static / Engine adapter: capture the traced op stream, re-emit
# fused subgraphs through the dispatcher, swap outputs
# --------------------------------------------------------------------------
class trace_rewrite:
    """Record ops dispatched inside the ``with`` body, then ``apply``
    the fusion pass to the captured stream.

    ``apply(out_tree)`` re-executes the fused steps — and every step
    downstream of a rewrite — through ``dispatch.call`` (so spmd
    trace scopes, cost accounting, and op metrics observe the fused
    program), then swaps the recomputed payloads into the output
    tensors. The superseded unfused values become dead code that XLA
    eliminates. Ops whose values are untouched by any rewrite keep
    their original payloads (zero re-trace cost).

    Caveat: the rewritten region is dispatched twice at TRACE time
    (original chain, then the fused replay), so trace-time-only
    telemetry (``FLAGS_perf_op_cost`` accumulators, per-op host-latency
    histograms) over-counts it by one trace. Compiled steady state
    never re-dispatches, and runtime attribution reads the compiled
    program's XLA cost analysis — both see exactly the fused program.
    """

    def __init__(self):
        self.steps: List[FusedStep] = []
        self._tensors: Dict[int, object] = {}
        self.stats: Optional[dict] = None

    def _hook(self, op_name, f, tensor_inputs, out_tensors, attrs=None):
        from ...core import dispatch
        for t in list(tensor_inputs) + list(out_tensors):
            self._tensors[id(t)] = t     # id stability + replay source
        s = dispatch._tls()
        amp = None
        if s.amp_level in ("O1", "O2"):
            amp = (s.amp_level, s.amp_dtype, set(s.amp_custom_white),
                   set(s.amp_custom_black))
        self.steps.append(FusedStep(
            name=op_name, fn=f,
            in_ids=tuple(id(t) for t in tensor_inputs),
            out_ids=tuple(id(t) for t in out_tensors),
            attrs=dict(attrs or {}),
            in_shapes=tuple(tuple(t.shape) for t in tensor_inputs),
            out_shapes=tuple(tuple(t.shape) for t in out_tensors),
            amp=amp))

    def __enter__(self):
        from ...core import dispatch
        dispatch.register_recorder_hook(self._hook)
        return self

    def __exit__(self, *exc):
        from ...core import dispatch
        dispatch.unregister_recorder_hook(self._hook)
        return False

    def apply(self, out_tree):
        import jax

        from ...core import dispatch
        from ...core.tensor import Tensor

        leaves, _ = jax.tree_util.tree_flatten(
            out_tree, is_leaf=lambda x: isinstance(x, Tensor))
        out_tensors = [l for l in leaves if isinstance(l, Tensor)]
        external = {id(t) for t in out_tensors}
        plan, stats = fuse_steps(self.steps, external)
        self.stats = stats
        if not stats["rewritten"]:
            return out_tree
        new_vals: Dict = {}          # vid -> recomputed Tensor

        def _inputs(st):
            ins = []
            for vid in st.in_ids:
                t = new_vals.get(vid)
                ins.append(t if t is not None else self._tensors[vid])
            return ins

        for st in plan:
            is_fused = bool(getattr(st, "pattern", ""))
            dirty = any(v in new_vals for v in st.in_ids)
            if not is_fused and not dirty:
                continue             # untouched: keep the original value
            amp = getattr(st, "amp", None)
            prev = dispatch.set_amp_state(*amp) if amp else None
            try:
                # attrs ride the replay so the spmd rules key on them
                # (transpose perm, reduce axis, …) — but ONLY as
                # dispatch metadata: a recorded step's fn is the
                # already attr-BOUND lowering the recorder hook saw
                # (dispatch closes attrs over it), so the replay fn
                # must swallow the kwargs dispatch would re-bind
                fn = st.fn
                if st.attrs:
                    fn = (lambda *xs, __f=st.fn, **_a: __f(*xs))
                outs = dispatch.call(st.name, fn, _inputs(st),
                                     attrs=st.attrs or None)
            finally:
                if prev is not None:
                    dispatch.restore_amp_state(prev)
            outs = outs if isinstance(outs, list) else [outs]
            for oid, t in zip(st.out_ids, outs):
                # keys are trace-time python object ids (ints) captured by
                # the recorder hook — never tensor values/hashes
                new_vals[oid] = t  # tpulint: disable=TPU203 id()-keyed replay env
        for t in out_tensors:
            repl = new_vals.get(id(t))
            if repl is not None:
                t._data = repl._data
        return out_tree


def rewrite_traced(call):
    """Convenience for the trace-time entry points: run ``call()``
    under a capture, apply the pass, return ``(out, stats)`` —
    a no-op passthrough when the flag is off."""
    if not enabled():
        return call(), None
    tr = trace_rewrite()
    with tr:
        out = call()
    out = tr.apply(out)
    return out, tr.stats


# --------------------------------------------------------------------------
# SOT adapter: rewrite the pending segment's node graph pre-compile
# --------------------------------------------------------------------------
class _SotStep:
    """Node-graph view of one SOT segment op (value ids are the
    ``("n", node, out)`` / ``("x", ext)`` refs the segment uses)."""

    __slots__ = ("name", "fn", "in_ids", "out_ids", "attrs",
                 "in_shapes", "out_shapes", "pattern")

    def __init__(self, name, fn, in_ids, out_ids, attrs, in_shapes,
                 out_shapes):
        self.name = name
        self.fn = fn
        self.in_ids = in_ids
        self.out_ids = out_ids
        self.attrs = attrs
        self.in_shapes = in_shapes
        self.out_shapes = out_shapes
        self.pattern = ""


def fuse_sot_nodes(nodes, out_refs):
    """Rewrite a SOT segment's node list; returns ``(plan, stats)``
    with plan steps executable over an env keyed by the original
    ``("n", node, out)`` slots — or ``(None, None)`` when nothing
    matched (the caller keeps its unfused ``seg_fn``)."""
    steps = []
    for nid, node in enumerate(nodes):
        op, f, in_refs, n_out, _ak, attrs, io_shapes = node
        in_shapes, out_shapes = io_shapes
        out_ids = tuple(("n", nid, k) for k in range(n_out))
        steps.append(_SotStep(
            op, f, tuple(tuple(r) for r in in_refs), out_ids,
            dict(attrs or {}), tuple(in_shapes), tuple(out_shapes)))
    external = {("n", nid, k) for nid, k in out_refs}
    plan, stats = fuse_steps(steps, external)
    if not stats["rewritten"]:
        return None, stats
    return plan, stats
