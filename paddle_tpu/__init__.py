"""paddle_tpu: a TPU-native deep learning framework.

Brand-new design with the capabilities of the PaddlePaddle reference
(define-by-run autograd, static capture, hybrid-parallel distributed
training), built on JAX/XLA/Pallas idioms: ops are jax lowerings fused by
XLA, the autograd tape records jax VJP closures, program capture jits whole
train steps, and parallelism is expressed over a jax.sharding.Mesh with
XLA collectives on ICI/DCN.
"""
from __future__ import annotations

import importlib

__version__ = "0.1.0"

from .core import dtype as _dtype_mod
from .core.dtype import (bfloat16, bool_ as bool8, complex64, complex128, float16,
                         float32, float64, int8, int16, int32, int64, uint8)
from .core.tensor import Tensor, as_tensor, is_tensor
from .core.dispatch import no_grad, enable_grad, set_grad_enabled_ctx as set_grad_enabled
from .core.generator import seed, get_rng_state, set_rng_state, Generator
from .core.flags import get_flags, set_flags, define_flag
from .core.place import (CPUPlace, CustomPlace, Place, TPUPlace, device_count,
                         get_device, is_compiled_with_tpu, set_device)
from .core import enforce

# Op surface (also attaches Tensor methods).
from .ops import *  # noqa: F401,F403
from .ops import creation as _creation
from .ops.creation import to_tensor
from .autograd import backward, grad, is_grad_enabled, PyLayer
from .batch import batch

CUDAPlace = TPUPlace  # source-compat alias: accelerator place


def flops(net, input_size=None, custom_ops=None, print_detail=False,
          inputs=None):
    from .hapi.dynamic_flops import flops as _flops
    return _flops(net, input_size, custom_ops=custom_ops,
                  print_detail=print_detail, inputs=inputs)


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_distribute():
    return True


def in_dynamic_mode():
    from .jit.api import in_capture_mode
    return not in_capture_mode()


def disable_static(place=None):
    return None


def enable_static():
    return None


def save(obj, path, protocol=4, **kwargs):
    from .framework.io import save as _save
    return _save(obj, path, protocol=protocol, **kwargs)


def load(path, **kwargs):
    from .framework.io import load as _load
    return _load(path, **kwargs)


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes=dtypes, input=input)


_LAZY_MODULES = {
    "nn", "optimizer", "amp", "io", "jit", "distributed", "vision", "metric",
    "profiler", "autograd", "incubate", "framework", "device", "static", "hapi",
    "distribution", "linalg", "fft", "signal", "sparse", "text", "onnx", "quantization",
    "models", "utils", "inference", "native", "audio", "geometric",
    "strings", "hub", "regularizer", "version", "sysconfig",
}

#: top-level names resolved lazily from submodules (avoids importing
#: hapi/nn at package import)
_LAZY_ATTRS = {
    "Model": ("paddle_tpu.hapi.model", "Model"),
    "callbacks": ("paddle_tpu.hapi", "callbacks"),
    "LazyGuard": ("paddle_tpu.nn.lazy_init", "LazyGuard"),
}


def __getattr__(name):
    if name in _LAZY_MODULES:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name in _LAZY_ATTRS:
        mod_name, attr = _LAZY_ATTRS[name]
        value = getattr(importlib.import_module(mod_name), attr)
        globals()[name] = value
        return value
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")
