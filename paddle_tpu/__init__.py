"""paddle_tpu: a TPU-native deep learning framework.

Brand-new design with the capabilities of the PaddlePaddle reference
(define-by-run autograd, static capture, hybrid-parallel distributed
training), built on JAX/XLA/Pallas idioms: ops are jax lowerings fused by
XLA, the autograd tape records jax VJP closures, program capture jits whole
train steps, and parallelism is expressed over a jax.sharding.Mesh with
XLA collectives on ICI/DCN.
"""
from __future__ import annotations

import importlib

__version__ = "0.1.0"

from .core import dtype as _dtype_mod
from .core.dtype import (bfloat16, bool_ as bool8, complex64, complex128, float16,
                         float32, float64, int8, int16, int32, int64, uint8,
                         float8_e4m3fn, float8_e5m2, iinfo, finfo,
                         get_default_dtype, set_default_dtype)
from .core.tensor import Tensor, as_tensor, is_tensor

import numpy as _np

#: paddle.dtype / paddle.bool — our dtypes ARE numpy dtype instances, so
#: the dtype "class" is np.dtype (isinstance(paddle.float32, paddle.dtype)
#: holds, matching the reference contract)
dtype = _np.dtype
bool = bool8  # noqa: A001 - reference exports `paddle.bool`
from .core.dispatch import no_grad, enable_grad, set_grad_enabled_ctx as set_grad_enabled
from .core.generator import seed, get_rng_state, set_rng_state, Generator
from .core.flags import get_flags, set_flags, define_flag
from .core.place import (CPUPlace, CustomPlace, Place, TPUPlace, device_count,
                         get_device, is_compiled_with_tpu, set_device)
from .core import enforce

# Op surface (also attaches Tensor methods).
from .ops import *  # noqa: F401,F403
from .ops import creation as _creation
from .ops.creation import to_tensor
from .autograd import backward, grad, is_grad_enabled, PyLayer
from .batch import batch

CUDAPlace = TPUPlace  # source-compat alias: accelerator place
CUDAPinnedPlace = CPUPlace  # pinned host memory: host-side here


def shape(x):
    """Shape of ``x`` as an int32 tensor (reference paddle.shape)."""
    return to_tensor(_np.asarray(x.shape, _np.int32))


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor print formatting (reference set_printoptions); applies to
    the numpy formatter Tensor.__repr__ uses."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def disable_signal_handler():
    """No-op (reference disables C++ fault handlers; none here)."""


def check_shape(shape_val, op_name="", expected_element_type=(int,)):
    """Shape validation helper (reference base/data_feeder.py
    check_shape: a shape is a list/tuple of ints or an int tensor)."""
    if isinstance(shape_val, Tensor):
        return
    if not isinstance(shape_val, (list, tuple)):
        raise TypeError(
            f"{op_name}: shape must be list/tuple/Tensor, got "
            f"{type(shape_val)}")
    for item in shape_val:
        if not isinstance(item, expected_element_type + (Tensor,)):
            raise TypeError(
                f"{op_name}: shape element must be int/Tensor, got "
                f"{type(item)}")


def flops(net, input_size=None, custom_ops=None, print_detail=False,
          inputs=None):
    from .hapi.dynamic_flops import flops as _flops
    return _flops(net, input_size, custom_ops=custom_ops,
                  print_detail=print_detail, inputs=inputs)


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_distribute():
    return True


def in_dynamic_mode():
    from .jit.api import in_capture_mode
    return not in_capture_mode()


def disable_static(place=None):
    return None


def enable_static():
    return None


def save(obj, path, protocol=4, **kwargs):
    from .framework.io import save as _save
    return _save(obj, path, protocol=protocol, **kwargs)


def load(path, **kwargs):
    from .framework.io import load as _load
    return _load(path, **kwargs)


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes=dtypes, input=input)


_LAZY_MODULES = {
    "nn", "optimizer", "amp", "io", "jit", "distributed", "vision", "metric", "fault",
    "profiler", "observability", "autograd", "incubate", "framework", "device", "static", "hapi",
    "distribution", "linalg", "fft", "signal", "sparse", "text", "onnx", "quantization",
    "models", "utils", "inference", "native", "audio", "geometric",
    "strings", "hub", "regularizer", "version", "sysconfig",
}

#: top-level names resolved lazily from submodules (avoids importing
#: hapi/nn at package import)
_LAZY_ATTRS = {
    "Model": ("paddle_tpu.hapi.model", "Model"),
    "callbacks": ("paddle_tpu.hapi", "callbacks"),
    "LazyGuard": ("paddle_tpu.nn.lazy_init", "LazyGuard"),
    "ParamAttr": ("paddle_tpu.nn.parameter", "ParamAttr"),
    "create_parameter": ("paddle_tpu.nn.parameter", "create_parameter"),
    "DataParallel": ("paddle_tpu.distributed.parallel", "DataParallel"),
    "get_cuda_rng_state": ("paddle_tpu.framework.random",
                           "get_cuda_rng_state"),
    "set_cuda_rng_state": ("paddle_tpu.framework.random",
                           "set_cuda_rng_state"),
}


def __getattr__(name):
    if name in _LAZY_MODULES:
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name in _LAZY_ATTRS:
        mod_name, attr = _LAZY_ATTRS[name]
        value = getattr(importlib.import_module(mod_name), attr)
        globals()[name] = value
        return value
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")
