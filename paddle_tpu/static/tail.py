"""static module tail: the remaining reference paddle.static surface.

Reference parity: python/paddle/static/__init__.py names previously
absent here. Notes on the TPU-native mappings:

* ``append_backward``/``gradients`` ride the eager tape (our static
  Program records ops over live tensors, so reverse-mode is the same
  engine, not a separate transpiler pass).
* scope objects hold host references (XLA owns device memory), so
  ``Scope``/``global_scope``/``scope_guard`` are thin registries.
* ``save_inference_model``/``load_inference_model`` produce the same
  StableHLO ``.pdmodel`` + ``.pdparams`` artifacts as ``jit.save`` —
  one deployment format for both capture paths.
* IPU classes raise, exactly like the reference does when paddle isn't
  compiled with IPU support.
"""
from __future__ import annotations

import contextlib
import os
import pickle
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor, as_tensor

__all__ = [
    "append_backward", "gradients", "Scope", "global_scope",
    "scope_guard", "BuildStrategy", "ipu_shard_guard",
    "IpuCompiledProgram", "IpuStrategy", "Print", "name_scope",
    "WeightNormParamAttr", "save", "load", "save_inference_model",
    "load_inference_model", "serialize_program", "serialize_persistables",
    "save_to_file", "deserialize_program", "deserialize_persistables",
    "load_from_file", "normalize_program", "load_program_state",
    "set_program_state", "cpu_places", "cuda_places", "xpu_places",
    "Variable", "create_global_var", "create_parameter", "accuracy",
    "auc", "device_guard", "set_ipu_shard", "ctr_metric_bundle",
]

Variable = Tensor  # reference static Variable ≙ tensor handle here


# ---------------------------------------------------------------- autodiff
def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """d(targets)/d(inputs) over the recorded tape (reference
    static/gradient.py gradients — here the eager engine IS the static
    autodiff, no transpiler pass)."""
    from ..autograd import grad as _grad
    outs = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    gouts = target_gradients
    if gouts is not None and not isinstance(gouts, (list, tuple)):
        gouts = [gouts]
    return list(_grad(list(outs), list(ins), grad_outputs=gouts,
                      retain_graph=True, allow_unused=True))


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Backward over the loss; returns [(param, grad)] (reference
    base/backward.py append_backward)."""
    from ..autograd import grad as _grad
    if parameter_list is None:
        from .program import default_main_program
        # captured (non-fed, non-produced) vars are the program's params
        parameter_list = [
            v for v in default_main_program()._captured.values()
            if getattr(v, "persistable", False) and not v.stop_gradient]
    params = [p for p in parameter_list if not p.stop_gradient]
    grads = _grad(loss, params, retain_graph=True, allow_unused=True)
    return list(zip(params, grads))


# ------------------------------------------------------------------ scopes
class Scope:
    """Host-side variable registry (reference core.Scope)."""

    def __init__(self):
        self._vars: Dict[str, object] = {}

    def var(self, name: str):
        return self._vars.setdefault(name, _ScopeVar())

    def find_var(self, name: str):
        return self._vars.get(name)

    def drop_kids(self):
        self._vars.clear()


class _ScopeVar:
    def __init__(self):
        self._value = None

    def get_tensor(self):
        return self._value

    def set_tensor(self, t):
        self._value = t


_global_scope = Scope()
_scope_stack: List[Scope] = []


def global_scope() -> Scope:
    return _scope_stack[-1] if _scope_stack else _global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


# ----------------------------------------------------------------- configs
class BuildStrategy:
    """Graph-build knobs (reference BuildStrategy). XLA owns fusion and
    scheduling, so the fields are accepted state with no further
    routing — documented, not silently meaningful."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.memory_optimize = True
        self.build_cse_optimized_program = False
        self.debug_graphviz_path = ""


class WeightNormParamAttr:
    """ParamAttr carrying a weight-norm dim hint (reference
    WeightNormParamAttr); consumed by nn.utils.weight_norm wrapping."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        from ..nn.parameter import ParamAttr
        self.dim = dim
        self.attr = ParamAttr(name=name, initializer=initializer,
                              learning_rate=learning_rate,
                              regularizer=regularizer,
                              trainable=trainable, need_clip=need_clip)


# --------------------------------------------------------------- IPU gates
_IPU_MSG = ("Can not use {} in paddle_tpu: this build targets TPU via "
            "XLA (the reference raises the same way when not compiled "
            "with IPU support)")


class IpuStrategy:
    def __init__(self):
        raise RuntimeError(_IPU_MSG.format("IpuStrategy"))


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise RuntimeError(_IPU_MSG.format("IpuCompiledProgram"))


@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    raise RuntimeError(_IPU_MSG.format("ipu_shard_guard"))
    yield  # pragma: no cover


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise RuntimeError(_IPU_MSG.format("set_ipu_shard"))


# ------------------------------------------------------------- misc guards
@contextlib.contextmanager
def name_scope(prefix=None):
    """Name prefixes don't change XLA programs; kept for parity."""
    yield


@contextlib.contextmanager
def device_guard(device=None):
    """Per-op device pinning is jax.device_put's job; accepted no-op."""
    yield


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print op (reference static.Print): prints and passes the
    tensor through."""
    t = as_tensor(input)
    head = message or ""
    vals = np.asarray(t.numpy()).reshape(-1)[:summarize]  # tpulint: disable=TPU101 — Print IS the host boundary: materializing values to render them is the op's contract
    print(f"{head} {t.name if print_tensor_name else ''} "
          f"shape={list(t.shape) if print_tensor_shape else ''} "
          f"values={vals}")
    return t


# ------------------------------------------------------------ vars/params
def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    import jax.numpy as jnp
    t = Tensor(jnp.full(tuple(shape), value, dtype=dtype),
               name=name, persistable=persistable)
    return t


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from ..nn.parameter import create_parameter as _cp
    if attr is None and name is not None:
        attr = name
    return _cp(shape, dtype=dtype, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


# ----------------------------------------------------------------- metrics
def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def _auc_in_graph(pred, lab, num_thresholds: int):
    """Batch ROC AUC as device ops (the thresholded-bin math of
    metric.Auc.update/accumulate, shapes static in num_thresholds) —
    stays async, traceable under to_static."""
    import jax.numpy as jnp
    if pred.ndim == 2 and pred.shape[1] == 2:      # (N, 2) proba layout
        pred = pred[:, 1]
    pred = pred.reshape(-1).astype(jnp.float32)
    # label TRUTHINESS, not value: the accumulator's labels.astype(bool)
    # counts each sample once whatever positive encoding it uses
    posf = (lab.reshape(-1) != 0).astype(jnp.float32)
    bins = jnp.clip((pred * num_thresholds).astype(jnp.int32), 0,
                    num_thresholds)
    stat_pos = jnp.zeros(num_thresholds + 1,
                         jnp.float32).at[bins].add(posf)
    stat_neg = jnp.zeros(num_thresholds + 1,
                         jnp.float32).at[bins].add(1.0 - posf)
    tot_pos = stat_pos.sum()
    tot_neg = stat_neg.sum()
    # integrate TPR over FPR from the highest threshold down, anchored
    # at the (0, 0) origin (same curve metric.Auc.accumulate walks)
    pos = jnp.concatenate([jnp.zeros(1), jnp.cumsum(stat_pos[::-1])])
    neg = jnp.concatenate([jnp.zeros(1), jnp.cumsum(stat_neg[::-1])])
    tpr = pos / jnp.maximum(tot_pos, 1.0)
    fpr = neg / jnp.maximum(tot_neg, 1.0)
    area = jnp.sum((fpr[1:] - fpr[:-1]) * (tpr[1:] + tpr[:-1]) * 0.5)
    # degenerate batches (single-class) score 0.0, as the accumulator does
    return jnp.where((tot_pos > 0) & (tot_neg > 0), area, 0.0)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """Batch AUC (reference static.auc returns (auc, batch_auc, state);
    here the stateless batch value twice + empty state tuple). Computed
    in-graph — no host materialization of predictions/labels."""
    val = Tensor(_auc_in_graph(as_tensor(input)._data,
                               as_tensor(label)._data, num_thresholds))
    return val, val, ()


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """CTR metric bundle (reference ctr_metric_bundle): (auc, sqrerr,
    abserr, prob, q, pos, total) — all reductions in-graph."""
    import jax.numpy as jnp
    pred = as_tensor(input)._data.reshape(-1).astype(jnp.float32)
    lab = as_tensor(label)._data.reshape(-1).astype(jnp.float32)
    a, _, _ = auc(input, label)
    sqrerr = Tensor(jnp.sum((pred - lab) ** 2))
    abserr = Tensor(jnp.sum(jnp.abs(pred - lab)))
    prob = Tensor(jnp.sum(pred))
    q = Tensor(jnp.sum(pred))
    pos = Tensor(jnp.sum(lab))
    total = Tensor(jnp.asarray(float(pred.shape[0]), jnp.float32))
    return a, sqrerr, abserr, prob, q, pos, total


# --------------------------------------------------------------- save/load
def _program_params(program) -> Dict[str, Tensor]:
    out = {}
    for i, v in enumerate(program._captured.values()):
        if isinstance(v, Tensor) and getattr(v, "persistable", False):
            out[v.name or f"var_{i}"] = v
    return out


def save(program, model_path, protocol=4, **configs):
    """Persist a program's persistable vars (reference static.save →
    ``.pdparams``)."""
    from ..framework.io import save as _save
    state = {k: v for k, v in _program_params(program).items()}
    _save(state, model_path + ".pdparams", protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """Restore persistable vars saved by ``save``."""
    from ..framework.io import load as _load
    state = _load(model_path + ".pdparams")
    params = _program_params(program)
    for k, v in state.items():
        if k in params:
            import jax.numpy as jnp
            params[k]._swap_payload(jnp.asarray(
                v._data if isinstance(v, Tensor) else v))


def load_program_state(model_path, var_list=None):
    from ..framework.io import load as _load
    state = _load(model_path + ".pdparams")
    return {k: np.asarray(v.numpy() if isinstance(v, Tensor) else v)
            for k, v in state.items()}


def set_program_state(program, state_dict):
    import jax.numpy as jnp
    params = _program_params(program)
    for k, v in state_dict.items():
        if k in params:
            params[k]._swap_payload(jnp.asarray(v))


def normalize_program(program, feeds, fetches, **kwargs):
    """Prune to the feed→fetch slice (reference normalize_program);
    the op-list replay already binds exactly that slice, so the program
    plus its endpoints IS the normalized form."""
    return {"program": program, "feeds": list(feeds),
            "fetches": list(fetches)}


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Export the feed→fetch computation as the jit.save artifact
    (StableHLO ``.pdmodel`` + ``.pdparams``; reference
    save_inference_model)."""
    from ..jit.api import save as _jit_save
    from . import InputSpec

    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    if program is None:
        from .program import default_main_program
        program = default_main_program()
    feed_ids = [id(v) for v in feed_vars]

    class _ProgramModule:
        training = False

        def forward(self, *xs):
            # trace the raw replay (program.run is the host API: it
            # converts outputs to numpy, which a tracer can't survive)
            arrays = [x._data if isinstance(x, Tensor) else x
                      for x in xs]
            cap_ids = list(program._captured.keys())
            cap_arrays = [t._data for t in program._captured.values()]
            env = program._replay_by_ids(
                [id(v) for v in feed_vars], arrays, cap_ids, cap_arrays)
            outs = [Tensor(env[id(v)]) for v in fetch_vars]
            return outs[0] if len(outs) == 1 else tuple(outs)

        __call__ = forward

        def state_dict(self):
            return dict(_program_params(program))

        def named_parameters(self):
            return list(_program_params(program).items())

    spec = [InputSpec.from_tensor(v) for v in feed_vars]
    _jit_save(_ProgramModule(), path_prefix, input_spec=spec)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Load the exported artifact; returns [callable_program,
    feed_names, fetch_handle] matching the reference triple."""
    from ..jit.api import load as _jit_load
    layer = _jit_load(path_prefix)
    n = getattr(layer, "n_inputs", 1)
    feed_names = [f"x{i}" for i in range(n)]
    return [layer, feed_names, ["out"]]


# ------------------------------------------------- serialization helpers
def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    if program is None:
        from .program import default_main_program
        program = default_main_program()
    return pickle.dumps({"ops": [r.name for r in
                                 program.global_block().ops],
                         "n_feeds": len(list(feed_vars)),
                         "n_fetches": len(list(fetch_vars))})


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    if program is None:
        from .program import default_main_program
        program = default_main_program()
    state = {k: np.asarray(v.numpy())
             for k, v in _program_params(program).items()}
    return pickle.dumps(state)


def deserialize_program(data: bytes):
    return pickle.loads(data)


def deserialize_persistables(program, data: bytes, executor=None):
    set_program_state(program, pickle.loads(data))


def save_to_file(path, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path) -> bytes:
    with open(path, "rb") as f:
        return f.read()


# ------------------------------------------------------------------ places
def cpu_places(device_count=None):
    from ..core.place import CPUPlace
    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """Accelerator places (TPU chips here; source-compat name)."""
    import jax

    from ..core.place import TPUPlace
    ids = device_ids if device_ids is not None else range(
        len(jax.devices()))
    return [TPUPlace(int(i)) for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)
