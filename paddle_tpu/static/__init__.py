"""Static-graph surface: InputSpec.

Reference: python/paddle/static/input.py InputSpec — declarative
shape/dtype/name of a program input, used by to_static and jit.save.
TPU-native: it maps directly to a jax.ShapeDtypeStruct; a -1/None dim is
exported as a symbolic dimension so one saved program serves any batch.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class InputSpec:
    def __init__(self, shape: Sequence[Optional[int]], dtype="float32",
                 name: Optional[str] = None, stop_gradient: bool = True):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = str(np.dtype(dtype)) if dtype != "bfloat16" \
            else "bfloat16"
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype!r}, "
                f"name={self.name!r})")


from .program import (CompiledProgram, Executor, Program,  # noqa: E402
                      data, default_main_program,
                      default_startup_program, program_guard)
from . import verifier  # noqa: E402,F401  (program verifier — ISSUE 15)
from . import nn  # noqa: E402,F401
from .nn import ExponentialMovingAverage, py_func  # noqa: E402,F401
from .tail import *  # noqa: E402,F401,F403
from . import tail as _tail  # noqa: E402

__all__ = ["InputSpec", "Program", "program_guard", "data", "Executor",
           "CompiledProgram", "default_main_program",
           "default_startup_program", "nn", "verifier",
           "ExponentialMovingAverage", "py_func"] + _tail.__all__
