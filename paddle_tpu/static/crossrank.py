"""Cross-rank static program diff (TPU45x) — compile-time desync
detection over rank-suffixed program dumps.

``flight.diff_ranks`` names a desynced rank only *after* the fleet
hangs; this module is the static complement. With
``PADDLE_TPU_PROGRAM_RECORD=<base>`` set, every compile path that
records the op-list IR (``static.Program.run`` first-compile,
``to_static``'s verifier ``trace_scope``) appends its serialized record
stream to ``<base>.r<rank>`` (flight's rank/world env helpers own the
suffix scheme). ``python -m tools.tpulint --cross-rank <base>`` then
diffs the per-rank programs rank-by-rank BEFORE anything has to hang:

* **TPU451** (error) — a program or collective is recorded by some
  ranks but not others (membership diverges);
* **TPU452** (error) — the same collective position carries different
  group/attrs/shape content across ranks;
* **TPU453** (error) — same collectives, different order;
* **TPU454** (warn) — the non-collective op streams themselves diverge
  (a rank-dependent branch in the traced step).

Every finding names the divergent rank and the first divergent sequence
number, mirroring the flight recorder's runtime verdict format.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from ..observability import flight as _flight
from .verifier import COLLECTIVE_OPS, Record, Report, _records_of

__all__ = ["RECORD_ENV", "FORMAT", "enabled", "dump_program",
           "maybe_dump", "note_collective", "reset", "load_dumps",
           "diff_programs", "run"]

#: env var naming the dump base path — rank-suffixed like the flight
#: recorder's PADDLE_TPU_FLIGHT_RECORD
RECORD_ENV = "PADDLE_TPU_PROGRAM_RECORD"
FORMAT = "paddle_tpu.program_record/1"

#: this process's recorded programs, keyed by base path (a process may
#: record into an explicit path AND the env-configured one)
_recorded: Dict[str, List[dict]] = {}

#: straight-line collective stream — eager collectives bypass the
#: dispatch recorder entirely (they only ride dispatch inside branch
#: traces), so recorded Programs never contain them; the seam in
#: ``collective._coll_begin`` notes them here and they dump as the
#: pseudo-program ``<collective-stream>``
_coll_stream: List[dict] = []


def enabled() -> bool:
    return bool(os.environ.get(RECORD_ENV))


def _json_safe(v):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


def _serialize(records, label: str) -> dict:
    entries = []
    for seq, r in enumerate(Record.of(x) for x in records):
        attrs = {k: _json_safe(v) for k, v in (r.attrs or {}).items()
                 if not k.startswith("_")}
        entries.append({
            "seq": seq,
            "name": r.name,
            "attrs": attrs,
            "in_shapes": [list(s) for s in r.in_shapes],
            "out_shapes": [list(s) for s in r.out_shapes],
            "in_dtypes": list(r.in_dtypes),
            "out_dtypes": list(r.out_dtypes),
            "loc": r.loc,
            # same definition as the verifier's branch-trace pass: the
            # collective seam always stamps the group attr, so a plain
            # tensor op that shares a name (indexing `scatter`) never
            # qualifies
            "collective": (r.name in COLLECTIVE_OPS
                           and "group" in (r.attrs or {})),
        })
    return {"label": label, "ops": entries}


def _write_rank_file(base: str) -> str:
    """Atomically (re)write ``<base>.r<rank>`` with every program —
    and, for the env-configured base, the straight-line collective
    stream — recorded so far."""
    progs = list(_recorded.get(base, ()))
    if _coll_stream and base == os.environ.get(RECORD_ENV):
        progs = progs + [{"label": "<collective-stream>",
                          "ops": list(_coll_stream)}]
    rank, world = _flight.rank_world()
    payload = {"format": FORMAT, "rank": rank, "world": world,
               "pid": os.getpid(), "programs": progs}
    path = _flight.record_path(base, rank=rank)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def dump_program(program_or_records, label: str,
                 base: Optional[str] = None) -> Optional[str]:
    """Serialize one recorded program and (re)write this rank's dump
    file ``<base>.r<rank>`` with every program recorded so far. Atomic
    replace, never raises — this rides compile paths."""
    try:
        base = base or os.environ.get(RECORD_ENV)
        if not base:
            return None
        records, _prog = _records_of(program_or_records)
        _recorded.setdefault(base, []).append(
            _serialize(records, label))
        return _write_rank_file(base)
    except Exception:                 # pragma: no cover - best effort
        return None


def note_collective(name: str, shape, dtype, group_id, **extra) -> None:
    """Record one straight-line collective launch into the rank's dump
    (env-gated; the ``collective._coll_begin`` seam calls this on every
    eager collective). The stream diffs like any other program: a rank
    running an extra / different / reordered collective is named with
    its first divergent sequence number BEFORE the fleet can hang on
    it. Never raises — this rides the collective hot path."""
    if not enabled():
        return
    try:
        attrs = {"group": int(group_id or 0)}
        for k, v in extra.items():
            attrs[k] = _json_safe(v)
        shape = [int(d) for d in (shape or ())]
        _coll_stream.append({
            "seq": len(_coll_stream), "name": name, "attrs": attrs,
            "in_shapes": [shape], "out_shapes": [shape],
            "in_dtypes": [str(dtype)], "out_dtypes": [str(dtype)],
            "loc": "", "collective": True})
        _write_rank_file(os.environ[RECORD_ENV])
    except Exception:                 # pragma: no cover - best effort
        pass


def reset() -> None:
    """Drop everything recorded so far in this process (programs AND
    the collective stream). For tests/drills that re-point
    ``PADDLE_TPU_PROGRAM_RECORD`` at a fresh base mid-process."""
    _recorded.clear()
    _coll_stream.clear()


def maybe_dump(program_or_records, label: str) -> Optional[str]:
    """Dump iff ``PADDLE_TPU_PROGRAM_RECORD`` is configured — the hook
    every compile path calls unconditionally."""
    if not enabled():
        return None
    return dump_program(program_or_records, label)


def load_dumps(base: str, world: Optional[int] = None) -> Dict[int, dict]:
    """{rank: payload} for every ``<base>.r<rank>`` present (flight's
    loader — same suffix scheme, format checked here)."""
    out = {}
    for r, payload in _flight.load_dumps(base, world).items():
        if payload.get("format") == FORMAT:
            out[r] = payload
    return out


# ---------------------------------------------------------------------------
# the diff
# ---------------------------------------------------------------------------
def _keyed_programs(payload) -> Dict[str, dict]:
    """label -> program, with repeat compiles of one label suffixed by
    occurrence (#1, #2, …) so per-signature recompiles line up."""
    seen: Dict[str, int] = {}
    out: Dict[str, dict] = {}
    for prog in payload.get("programs", ()):
        label = str(prog.get("label", "<program>"))
        k = seen.get(label, 0)
        seen[label] = k + 1
        out[label if k == 0 else f"{label}#{k}"] = prog
    return out


def _coll_sig(e) -> tuple:
    attrs = e.get("attrs") or {}
    return (tuple(sorted((k, _json_safe(v)) for k, v in attrs.items())),
            tuple(tuple(s) for s in e.get("in_shapes", ())),
            tuple(e.get("in_dtypes", ())))


def _diff_one(key: str, ref_rank: int, ref: dict, rank: int, other: dict,
              report: Report):
    """Compare one program label between the reference rank and
    ``rank``; emit at most one finding per code family."""
    rc = [e for e in ref.get("ops", ()) if e.get("collective")]
    oc = [e for e in other.get("ops", ()) if e.get("collective")]

    def first_div(a, b, sig):
        for i in range(min(len(a), len(b))):
            if sig(a[i]) != sig(b[i]):
                return i
        return min(len(a), len(b)) if len(a) != len(b) else None

    name_div = first_div(rc, oc, lambda e: e["name"])
    if name_div is not None:
        a_names = sorted(e["name"] for e in rc)
        b_names = sorted(e["name"] for e in oc)
        div = oc[name_div] if name_div < len(oc) else (
            rc[name_div] if name_div < len(rc) else None)
        seq = div["seq"] if div else name_div
        op = div["name"] if div else "<missing>"
        loc = div.get("loc", "") if div else ""
        if a_names != b_names:
            report.add(
                "TPU451", seq, op,
                f"program {key!r}: rank={rank} seq={seq} — collective "
                f"sequence membership differs from rank {ref_rank} "
                f"({len(oc)} vs {len(rc)} collectives; first "
                f"divergence at collective #{name_div}: rank {rank} "
                f"runs {op!r})", loc)
        else:
            report.add(
                "TPU453", seq, op,
                f"program {key!r}: rank={rank} seq={seq} — same "
                f"collectives as rank {ref_rank} but the order "
                f"diverges at collective #{name_div} ({op!r})", loc)
        return
    content_div = first_div(rc, oc, _coll_sig)
    if content_div is not None:
        div = oc[content_div]
        report.add(
            "TPU452", div["seq"], div["name"],
            f"program {key!r}: rank={rank} seq={div['seq']} — "
            f"collective {div['name']!r} differs from rank {ref_rank} "
            f"in group/attrs/shape at the same position "
            f"(#{content_div}): {_coll_sig(div)} vs "
            f"{_coll_sig(rc[content_div])}", div.get("loc", ""))
        return
    ra, oa = ref.get("ops", ()), other.get("ops", ())
    op_div = first_div(ra, oa, lambda e: (e["name"],
                                          tuple(tuple(s) for s in
                                                e.get("out_shapes", ()))))
    if op_div is not None:
        div = oa[op_div] if op_div < len(oa) else ra[op_div]
        report.add(
            "TPU454", div["seq"], div["name"],
            f"program {key!r}: rank={rank} seq={div['seq']} — op "
            f"stream diverges from rank {ref_rank} at op "
            f"#{op_div} ({len(oa)} vs {len(ra)} ops): rank {rank} "
            f"records {div['name']!r}", div.get("loc", ""))


def diff_programs(dumps: Dict[int, dict]) -> Report:
    """Rank-by-rank static diff of program dumps; the lowest rank is
    the reference. Returns a verifier :class:`Report` (TPU45x codes),
    empty when every rank recorded identical programs."""
    report = Report(label="cross-rank")
    if len(dumps) < 2:
        report.stats = {"ranks": sorted(dumps), "programs": 0}
        return report
    ranks = sorted(dumps)
    ref_rank = ranks[0]
    keyed = {r: _keyed_programs(dumps[r]) for r in ranks}
    all_keys: List[str] = []
    for r in ranks:
        for k in keyed[r]:
            if k not in all_keys:
                all_keys.append(k)
    for key in all_keys:
        have = [r for r in ranks if key in keyed[r]]
        missing = [r for r in ranks if key not in keyed[r]]
        if missing:
            minority = have if len(have) < len(missing) else missing
            report.add(
                "TPU451", -1, "<program>",
                f"program {key!r} recorded by ranks {have} but not by "
                f"ranks {missing} — rank={minority[0]} diverges from "
                f"the fleet (rank-dependent compile path)")
            continue
        ref = keyed[ref_rank][key]
        for r in ranks[1:]:
            _diff_one(key, ref_rank, ref, r, keyed[r][key], report)
    report.stats = {"ranks": ranks, "programs": len(all_keys)}
    return report


def run(base: str, world: Optional[int] = None, quiet: bool = False) -> int:
    """CLI entry for ``tpulint --cross-rank``: load + diff + print.
    Returns the number of findings (0 = every rank agrees)."""
    dumps = load_dumps(base, world)
    if not dumps:
        print(f"cross-rank: no program dumps found at {base}.r<rank> "
              f"(set {RECORD_ENV} on the launch)")
        return 1
    report = diff_programs(dumps)
    if not quiet:
        n = report.stats.get("programs", 0)
        if report.ok:
            print(f"cross-rank: {len(dumps)} rank dump(s), {n} "
                  f"program(s) — all ranks agree")
        else:
            print(report.render())
    return len(report.findings)
