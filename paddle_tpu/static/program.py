"""Static-graph Program / Executor.

Reference: python/paddle/static/ — Program (framework.py), program_guard,
data (input.py), Executor (executor.py), default_main_program. The
reference builds a ProgramDesc of OpDescs that the C++ interpreter runs;
here a Program records the dispatched ops of its `program_guard` block
(op name + attr-bound lowering + value ids) — an inspectable op-list IR —
and `Executor.run` replays it over feeds as ONE `jax.jit` program per feed
signature (the compiled-program/Plan cache). Training-path capture stays
on `jit.to_static`; this surface serves reference-style
construct-then-execute code.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor

_state = threading.local()


class _OpRecord:
    __slots__ = ("name", "fn", "in_ids", "out_ids", "attrs", "in_shapes",
                 "out_shapes", "in_dtypes", "out_dtypes", "loc")

    def __init__(self, name, fn, in_ids, out_ids, attrs=None,
                 in_shapes=(), out_shapes=(), in_dtypes=(),
                 out_dtypes=(), loc=""):
        self.name = name
        self.fn = fn
        self.in_ids = in_ids
        self.out_ids = out_ids
        # semantic attrs + shapes at record time: the spmd propagation
        # pass (distributed.spmd.propagate) reads the op list as an IR
        # and needs axis/transpose attrs and dim counts per value;
        # dtypes + the recording source line feed the program verifier
        # (static.verifier) — contract checks and finding provenance
        self.attrs = dict(attrs or {})
        self.in_shapes = tuple(in_shapes)
        self.out_shapes = tuple(out_shapes)
        self.in_dtypes = tuple(in_dtypes)
        self.out_dtypes = tuple(out_dtypes)
        self.loc = loc

    def __repr__(self):
        ins = ", ".join(f"v{i}" for i in self.in_ids)
        outs = ", ".join(f"v{o}" for o in self.out_ids)
        return f"{outs} = {self.name}({ins})"


class _Block:
    """Single-block program body (reference Block; control flow in this
    design lives inside lowerings as lax ops, so one block suffices)."""

    def __init__(self):
        self.ops: List[_OpRecord] = []

    def __repr__(self):
        return "\n".join(f"  {op!r}" for op in self.ops)


class Program:
    """Recorded op-list program (reference static.Program)."""

    def __init__(self):
        self._block = _Block()
        self.feed_vars: Dict[str, int] = {}   # data() name -> value id
        self._feed_shapes: Dict[str, tuple] = {}
        self._feed_dtypes: Dict[str, str] = {}
        # constants/parameters read by ops but produced by no op and not
        # fed: id -> live Tensor (weights update in place between runs)
        self._captured: Dict[int, Tensor] = {}
        self._keepalive: List[Tensor] = []  # id stability across guards
        self._produced: set = set()  # incremental: capture stays O(n)
        self._jit_cache: Dict[tuple, "jax._src.stages.Wrapped"] = {}
        #: stats of the most recent fusion-pass application (run() with
        #: FLAGS_enable_fusion; None = pass never ran on this program)
        self.fusion_stats: Optional[dict] = None

    # -- construction -----------------------------------------------------
    def _record(self, op_name, fn, tensor_inputs, out_tensors, attrs=None):
        in_ids = [id(t) for t in tensor_inputs]
        out_ids = [id(t) for t in out_tensors]
        for t in tensor_inputs:
            if (id(t) not in self._produced
                    and id(t) not in self.feed_vars.values()
                    and id(t) not in self._captured):
                self._captured[id(t)] = t
        self._produced.update(out_ids)
        self._keepalive.extend(out_tensors)
        from . import verifier as _verifier
        # source provenance only when the verifier can consume it:
        # FLAGS_verify_programs=off restores the pre-verifier record
        # cost (no per-op stack walk)
        loc = _verifier.user_loc() if _verifier.mode() != "off" else ""
        self._block.ops.append(_OpRecord(
            op_name, fn, in_ids, out_ids, attrs,
            [tuple(t.shape) for t in tensor_inputs],
            [tuple(t.shape) for t in out_tensors],
            [str(t.dtype) for t in tensor_inputs],
            [str(t.dtype) for t in out_tensors],
            loc))

    def global_block(self):
        return self._block

    def list_vars(self):
        seen = []
        for op in self._block.ops:
            for i in op.in_ids + op.out_ids:
                if i not in seen:
                    seen.append(i)
        return seen

    def to_string(self, throw_on_error=False, with_details=False):
        feeds = ", ".join(f"{n}: v{i}{list(self._feed_shapes[n])}"
                          for n, i in self.feed_vars.items())
        return (f"Program(feeds=[{feeds}], "
                f"params={len(self._captured)})\n{self._block!r}")

    __repr__ = to_string

    # -- execution --------------------------------------------------------
    def run(self, feed: Dict[str, np.ndarray], fetch_ids: List[int]):
        names = sorted(self.feed_vars)
        missing = [n for n in names if n not in feed]
        if missing:
            raise KeyError(f"missing feeds: {missing}")
        arrays = []
        for n in names:
            a = jnp.asarray(feed[n])
            declared = self._feed_dtypes.get(n)
            if declared and str(a.dtype) != declared:
                a = a.astype(np.dtype(declared))  # honor the declaration
            arrays.append(a)
        # the signature includes the captured-id set: extending the
        # program with new weights must invalidate compiled closures —
        # and, when graph fusion is on, the pass fingerprint (a fused
        # and an unfused compile of one program never share an entry)
        sig = (tuple((n, a.shape, str(a.dtype))
                     for n, a in zip(names, arrays)), tuple(fetch_ids),
               tuple(self._captured.keys()))
        from ..compile import fusion as _fusion
        fuse = _fusion.enabled()
        if fuse:
            sig = sig + (_fusion.fingerprint(),)
        if sig not in self._jit_cache:
            from . import crossrank as _crossrank
            # rank-suffixed program dump (PADDLE_TPU_PROGRAM_RECORD):
            # the static substrate tpulint --cross-rank diffs across a
            # multi-process launch before anything can hang
            _crossrank.maybe_dump(self, label="static.Program")
            from . import verifier as _verifier
            if _verifier.mode() != "off":
                # pre-compile verification (FLAGS_verify_programs):
                # strict raises the framework's error naming the op +
                # source line before jax.jit ever sees the program —
                # including the TPU901 static peak-HBM-over-capacity
                # check (static.liveness)
                _verifier.enforce(_verifier.check(
                    self, fetch_ids=list(fetch_ids),
                    label="static.Program"))
            feed_ids = [self.feed_vars[n] for n in names]
            cap_ids = list(self._captured.keys())
            ops_plan = None
            if fuse:
                # fetched ids are the external set: a fetch of a value
                # interior to a candidate chain rejects that fusion
                ops_plan, self.fusion_stats = _fusion.fuse_program_ops(
                    self._block.ops, fetch_ids)

            def compiled(feed_arrays, cap_arrays, _ops=ops_plan):
                env = self._replay_by_ids(feed_ids, feed_arrays, cap_ids,
                                          cap_arrays, ops=_ops)
                return [env[i] for i in fetch_ids]

            self._jit_cache[sig] = jax.jit(compiled)
        cap_arrays = [t._data for t in self._captured.values()]
        outs = self._jit_cache[sig](arrays, cap_arrays)
        return [np.asarray(o) for o in outs]  # tpulint: disable=TPU104 — Program.run returns numpy by contract (reference Executor.run): the fetch IS the host boundary

    def _replay_by_ids(self, feed_ids, feed_arrays, cap_ids, cap_arrays,
                       ops=None):
        env = dict(zip(feed_ids, feed_arrays))
        env.update(zip(cap_ids, cap_arrays))
        # ``ops`` overrides the block's op list (the fusion pass hands a
        # rewritten plan whose FusedSteps replay like _OpRecords)
        for op in (self._block.ops if ops is None else ops):
            args = [env[i] for i in op.in_ids]
            out = op.fn(*args)
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            for oid, val in zip(op.out_ids, outs):
                env[oid] = val
        return env


def _current() -> Optional[Program]:
    return getattr(_state, "program", None)


_default_main = Program()
_default_startup = Program()


def default_main_program() -> Program:
    return _current() or _default_main


def default_startup_program() -> Program:
    return _default_startup


class program_guard:
    """Record the block's dispatched ops into ``main`` (reference
    static.program_guard)."""

    def __init__(self, main: Program, startup: Optional[Program] = None):
        self.main = main
        self.startup = startup

    def __enter__(self):
        self._prev = _current()
        # suspend the outer program's recorder: nested guards record into
        # the INNER program only (reference nested program_guard behavior)
        self._prev_hook = (self._prev._record
                           if self._prev is not None else None)
        if self._prev_hook is not None:
            dispatch.unregister_recorder_hook(self._prev_hook)
        _state.program = self.main
        self._hook = self.main._record
        dispatch.register_recorder_hook(self._hook)
        return self

    def __exit__(self, *exc):
        dispatch.unregister_recorder_hook(self._hook)
        if self._prev_hook is not None:
            dispatch.register_recorder_hook(self._prev_hook)
        _state.program = self._prev
        return False


def data(name: str, shape, dtype="float32", lod_level=0):
    """Declare a program input (reference static.data). Returns a
    placeholder Tensor (zeros at the example shape) whose id marks the
    feed slot; -1/None dims replay at whatever size the feed supplies."""
    prog = _current()
    if prog is None:
        raise RuntimeError("static.data must be called under program_guard")
    example = tuple(1 if (s is None or s == -1) else int(s) for s in shape)
    t = Tensor(jnp.zeros(example, dtype=np.dtype(dtype)), name=name)
    prog._keepalive.append(t)  # pin the id: reuse would alias the slot
    prog.feed_vars[name] = id(t)
    prog._feed_shapes[name] = tuple(
        -1 if (s is None or s == -1) else int(s) for s in shape)
    prog._feed_dtypes[name] = str(np.dtype(dtype))  # normalized
    return t


class Executor:
    """Replay a Program over feeds (reference static.Executor). The place
    argument is accepted for parity; XLA owns placement."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, return_numpy=True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_ids = [id(t) for t in fetch_list]
        outs = program.run(feed, fetch_ids)
        if return_numpy:
            return outs
        return [Tensor(jnp.asarray(o)) for o in outs]


class CompiledProgram:
    """Parity alias (reference CompiledProgram) — every Program here is
    compiled per feed signature already."""

    def __init__(self, program: Program, build_strategy=None):
        self.program = program
