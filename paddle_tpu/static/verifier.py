"""Whole-program verifier over the recorded op-list IR.

Every diagnostic layer before this one is *runtime*: the collective
flight recorder names a desynced rank after the fleet hangs, the
donation registry raises when the stale read executes, and shape/dtype
mistakes surface as raw XLA errors deep inside ``to_static``. This
module is the static complement — the same class of pre-execution
verification GSPMD-style partitioners and MPI deadlock checkers (MUST)
run over their IRs — applied to the op-list IR every compile path in
this framework already records (``static/program.py`` ``_OpRecord``,
the ``to_static`` trace stream, SOT segment nodes, fusion plans).

Four pass families, each with its own code block (``CODES``):

* **TPU7xx — contract**: per-op validation against registry metadata.
  Unknown ops, broadcast-illegal elementwise shapes, silent float
  downcasts (the exact bug class the round-15 fusion review fixed by
  hand), dead/unfetchable ops, in-place-target aliasing that makes a
  replay read a stale pre-mutation value.
* **TPU4xx — collective safety**: static desync detection. Control-flow
  ops (``static.nn`` cond / while_loop / switch_case) carry their
  branch traces; arms whose collective sequences differ in membership,
  order, or group/shape content are flagged — the static complement of
  ``flight.diff_ranks`` — and collectives under a data-dependent loop
  trip count are warned about.
* **TPU5xx — sharding/mesh**: given a mesh + specs, the round-13
  propagation pass runs offline and pre-flights mesh-divisibility
  violations, replicate-fallback ops on the hot path, and ``Partial``
  (reduce-pending) values consumed without a reduction
  (``ShardingPlan.partial_env``).
* **TPU6xx — donation hazards**: parameters marked for donation that
  the traced step itself host-reads — the read the round-17 runtime
  registry would only catch once the stale buffer is touched.
* **TPU8xx — cross-stage desync**: the pipeline partitioner renders
  each stage as a record list with explicit ``send``/``recv`` boundary
  records (``distributed.pipeline.StagePartition.stage_records``);
  :func:`check_stages` statically matches every stage's sends against
  the next stage's recvs — count, shape/dtype, and sequence order —
  the compile-time complement of ``flight.diff_ranks``, per stage.

Wired into all three compile paths behind ``FLAGS_verify_programs``
(default ``warn``; ``strict`` raises :class:`ProgramVerifierError`
naming the op and its source line before XLA ever sees the program;
``off`` disables). ``verifier.check(program, mesh=...)`` is the offline
entry; ``python -m tools.tpulint --programs`` runs it over the
framework-traced ladder programs.
"""
from __future__ import annotations

import os
import sys
import warnings
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

__all__ = ["CODES", "Finding", "Report", "ProgramVerifierError",
           "ProgramVerifierWarning", "check", "check_records",
           "check_stages", "audit_step", "trace_scope", "mode",
           "enforce", "COLLECTIVE_OPS"]

#: every code the verifier can emit (severity: error = strict raises,
#: warn = reported but never fatal)
CODES = {
    # TPU4xx — collective safety (static desync analysis)
    "TPU401": ("warn", "collective under a data-dependent while_loop "
                       "(per-rank trip counts can diverge)"),
    "TPU402": ("error", "branch arms trace mismatched collective "
                        "sequences (static desync)"),
    "TPU403": ("error", "collective group/axes/shape differs between "
                        "branch arms at the same position"),
    "TPU404": ("error", "collective ordering diverges between branch "
                        "arms"),
    # TPU5xx — sharding/mesh pre-flight
    "TPU501": ("error", "sharded dimension not divisible by its mesh "
                        "axes"),
    "TPU502": ("warn", "op with no sharding rule on the hot path "
                       "(replicate fallback)"),
    "TPU503": ("warn", "Partial (reduce-pending) value consumed "
                       "without a reduction"),
    # TPU6xx — donation hazards
    "TPU601": ("error", "donated parameter host-read inside the traced "
                        "step (stale after donation)"),
    # TPU7xx — program contract
    "TPU700": ("warn", "op not present in the registry"),
    "TPU701": ("error", "operand shapes are not broadcast-compatible "
                        "for this op"),
    "TPU702": ("warn", "silent float downcast (f32 operand, narrower "
                       "output) outside the AMP white-list"),
    "TPU703": ("warn", "dead op: no output is consumed or fetched"),
    "TPU704": ("warn", "in-place target read after mutation (replay "
                       "sees the stale pre-mutation value)"),
    "TPU705": ("error", "fetched value is produced by no op and is "
                        "neither a feed nor a captured parameter"),
    # TPU8xx — pipeline cross-stage desync
    "TPU801": ("error", "adjacent pipeline stages disagree on the "
                        "number of boundary sends/recvs"),
    "TPU802": ("error", "pipeline boundary value shape/dtype differs "
                        "between send and matching recv"),
    "TPU803": ("error", "pipeline send/recv sequence mismatch (peer "
                        "or transfer order disagrees between adjacent "
                        "stages)"),
    # TPU45x — cross-rank program diff (static.crossrank over
    # rank-suffixed PADDLE_TPU_PROGRAM_RECORD dumps)
    "TPU451": ("error", "ranks recorded different collective "
                        "sequences (membership differs — static "
                        "cross-rank desync)"),
    "TPU452": ("error", "collective group/attrs/shape differs between "
                        "ranks at the same sequence position"),
    "TPU453": ("error", "collective ordering diverges between ranks"),
    "TPU454": ("warn", "non-collective op streams diverge between "
                       "ranks (rank-dependent branch in the traced "
                       "step)"),
    # TPU75x — setitem/scatter alias checking (static.liveness)
    "TPU751": ("error", "region write overlaps a later read of the "
                        "pre-write value (stale replay)"),
    "TPU752": ("error", "in-place write through a donated buffer"),
    "TPU753": ("warn", "in-place write through a view: XLA never "
                       "updates the base (diverges from reference "
                       "in-place view semantics)"),
    "TPU754": ("warn", "data-dependent write indices: overlap with a "
                       "later read of the pre-write value is "
                       "unprovable"),
    # TPU9xx — static memory (liveness & peak-HBM, static.liveness)
    "TPU901": ("error", "static peak HBM exceeds chip capacity "
                        "(program cannot fit — raised before XLA "
                        "compiles)"),
    "TPU902": ("warn", "static peak HBM is >= 90% of chip capacity"),
}

#: op names the collective pass treats as fleet-wide synchronization
#: points (the ``distributed.communication`` surface; recorded into
#: branch traces by the collective layer's branch-trace seam)
COLLECTIVE_OPS = frozenset({
    "all_reduce", "all_gather", "all_gather_object", "reduce_scatter",
    "broadcast", "reduce", "scatter", "alltoall", "alltoall_single",
    "barrier", "send", "recv", "isend", "irecv",
})

#: control-flow ops whose branch arms must agree on collectives
_ARM_OPS = ("conditional_block", "switch_case")
_LOOP_OPS = ("while_loop",)

#: ops exempt from the downcast check: the cast IS the semantics
_CAST_OPS = frozenset({"cast", "astype", "to", "type_as", "amp_cast"})

#: binary elementwise ops whose output is the numpy broadcast of the
#: inputs — the contract the fusion pass and synthetic IRs must honor
_ELEMENTWISE_BINARY = frozenset({
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "pow", "floor_divide", "remainder", "fmax", "fmin",
})


class ProgramVerifierError(RuntimeError):
    """FLAGS_verify_programs=strict: the program failed verification.
    The message names every finding with its op index and source line —
    raised before XLA ever sees the program."""


class ProgramVerifierWarning(UserWarning):
    """Default (warn) mode: findings are reported through this
    category so callers can filter or escalate them."""


@dataclass
class Finding:
    code: str
    op_index: int            # -1 = program-level
    op_name: str
    message: str
    loc: str = ""            # "file.py:123" provenance of the op

    @property
    def severity(self) -> str:
        return CODES.get(self.code, ("error", ""))[0]

    def render(self) -> str:
        where = f"op#{self.op_index} {self.op_name}" \
            if self.op_index >= 0 else "program"
        at = f" ({self.loc})" if self.loc else ""
        return f"{self.code} {where}{at}: {self.message}"


@dataclass
class Report:
    label: str = "program"
    findings: List[Finding] = field(default_factory=list)
    #: per-pass stats (ops walked, passes run) for tooling
    stats: Dict[str, object] = field(default_factory=dict)

    def add(self, code, op_index, op_name, message, loc=""):
        self.findings.append(Finding(code, op_index, op_name, message,
                                     loc))

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "warn"]

    @property
    def ok(self) -> bool:
        return not self.findings

    def codes(self) -> List[str]:
        return sorted({f.code for f in self.findings})

    def render(self) -> str:
        head = f"verifier: {len(self.findings)} finding(s) in " \
               f"{self.label}"
        return "\n".join([head] + [f"  {f.render()}"
                                   for f in self.findings])


def mode() -> str:
    """Current FLAGS_verify_programs mode: off | warn | strict."""
    from ..core import flags
    v = str(flags.get_flag("verify_programs") or "off").lower()
    if v in ("", "0", "false", "off", "none"):
        return "off"
    if v in ("strict", "raise", "error"):
        return "strict"
    return "warn"


def enforce(report: Report, mode_: Optional[str] = None):
    """Apply the flag policy to a report: strict raises
    :class:`ProgramVerifierError` when any error-severity finding
    exists; otherwise findings surface as one
    :class:`ProgramVerifierWarning`."""
    m = mode_ if mode_ is not None else mode()
    if m == "off" or report.ok:
        return report
    if m == "strict" and report.errors:
        raise ProgramVerifierError(report.render())
    warnings.warn(report.render(), ProgramVerifierWarning, stacklevel=3)
    return report


# ---------------------------------------------------------------------------
# Source provenance: first frame outside the framework's capture
# machinery — the line the finding should point the user at.
# ---------------------------------------------------------------------------
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SKIP_PARTS = (os.path.join("paddle_tpu", "core"),
               os.path.join("paddle_tpu", "static"),
               os.path.join("paddle_tpu", "jit"),
               os.path.join("paddle_tpu", "compile"),
               os.path.join("paddle_tpu", "distributed", "spmd"),
               os.path.join("paddle_tpu", "ops"),
               os.path.join("paddle_tpu", "nn", "functional"))


def user_loc(max_depth: int = 30) -> str:
    """Walk up the stack past dispatch/capture frames to the first
    user-owned line (best effort; "" when everything is framework)."""
    try:
        f = sys._getframe(2)
    except ValueError:                       # pragma: no cover
        return ""
    first_fw = ""
    for _ in range(max_depth):
        if f is None:
            break
        fn = f.f_code.co_filename
        if not any(p in fn for p in _SKIP_PARTS):
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        if not first_fw and _PKG_DIR in fn:
            first_fw = f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return first_fw


# ---------------------------------------------------------------------------
# Record normalization: every compile path's steps qualify
# ---------------------------------------------------------------------------
class Record:
    """Uniform view of one IR step (``_OpRecord`` / ``FusedStep`` /
    verifier trace entries / hand-built fixture records)."""

    __slots__ = ("name", "fn", "in_ids", "out_ids", "attrs", "in_shapes",
                 "out_shapes", "in_dtypes", "out_dtypes", "loc")

    def __init__(self, name, in_ids=(), out_ids=(), attrs=None,
                 in_shapes=(), out_shapes=(), in_dtypes=(),
                 out_dtypes=(), loc="", fn=None):
        self.name = name
        self.fn = fn
        self.in_ids = tuple(in_ids)
        self.out_ids = tuple(out_ids)
        self.attrs = dict(attrs or {})
        self.in_shapes = tuple(tuple(s) for s in in_shapes)
        self.out_shapes = tuple(tuple(s) for s in out_shapes)
        self.in_dtypes = tuple(str(d) for d in in_dtypes)
        self.out_dtypes = tuple(str(d) for d in out_dtypes)
        self.loc = loc

    @classmethod
    def of(cls, step) -> "Record":
        if isinstance(step, cls):
            return step
        return cls(
            name=step.name, fn=getattr(step, "fn", None),
            in_ids=step.in_ids, out_ids=step.out_ids,
            attrs=getattr(step, "attrs", None) or {},
            in_shapes=getattr(step, "in_shapes", ()) or (),
            out_shapes=getattr(step, "out_shapes", ()) or (),
            in_dtypes=getattr(step, "in_dtypes", ()) or (),
            out_dtypes=getattr(step, "out_dtypes", ()) or (),
            loc=getattr(step, "loc", "") or "")


def _records_of(program_or_steps):
    """(records, program-or-None) from either entry form."""
    block = getattr(program_or_steps, "global_block", None)
    if block is not None:
        return ([Record.of(op) for op in block().ops], program_or_steps)
    return ([Record.of(op) for op in program_or_steps], None)


# ---------------------------------------------------------------------------
# Pass 1 — contract (TPU7xx)
# ---------------------------------------------------------------------------
def _broadcastable(a, b) -> bool:
    for x, y in zip(reversed(a), reversed(b)):
        if x != y and x != 1 and y != 1:
            return False
    return True


def _float_key(dt: str) -> int:
    return {"float16": 16, "bfloat16": 16, "float32": 32,
            "float64": 64}.get(dt, 0)


def _contract_pass(records: List[Record], report: Report,
                   fetch_ids=None, known_ids=()):
    from ..ops.registry import OPS
    from ..core.dispatch import AMP_WHITE_OPS
    inplace_targets = {d.inplace_variant for d in OPS.values()
                       if d.inplace_variant}
    try:
        from ..ops.inplace import INPLACE_OF
        inplace_targets.update(INPLACE_OF)
    except Exception:                # pragma: no cover - partial import
        pass
    # the region write family is owned by the TPU75x alias pass
    # (static.liveness), which proves disjoint write/read regions safe —
    # the whole-buffer TPU704 check would double-flag them
    from .liveness import WRITE_FAMILY as _wf
    inplace_targets -= _wf
    produced: Dict[int, int] = {}
    consumed_after: Dict[int, int] = {}
    for i, r in enumerate(records):
        for v in r.in_ids:
            consumed_after[v] = i
    known = set(known_ids)
    fetch_set = set(fetch_ids) if fetch_ids is not None else set()
    for i, r in enumerate(records):
        if r.name not in OPS and r.name not in COLLECTIVE_OPS:
            report.add("TPU700", i, r.name,
                       f"op {r.name!r} is not a registered op — the "
                       f"registry carries no contract (cost/sharding/"
                       f"doc) for it", r.loc)
        # broadcast legality on the elementwise contract
        if (r.name in _ELEMENTWISE_BINARY and len(r.in_shapes) >= 2):
            a, b = r.in_shapes[0], r.in_shapes[1]
            if not _broadcastable(a, b):
                report.add("TPU701", i, r.name,
                           f"operand shapes {a} and {b} do not "
                           f"broadcast", r.loc)
        # silent float downcast (round-15 fusion-review bug class)
        if (r.out_dtypes and r.in_dtypes and r.name not in _CAST_OPS
                and r.name.lower() not in AMP_WHITE_OPS):
            widest = max((_float_key(d) for d in r.in_dtypes),
                         default=0)
            for o, od in enumerate(r.out_dtypes):
                ok = _float_key(od)
                if widest and ok and ok < widest:
                    report.add(
                        "TPU702", i, r.name,
                        f"output {o} is {od} while a "
                        f"{max(r.in_dtypes, key=_float_key)} operand "
                        f"enters — a silent downcast unless this op is "
                        f"AMP-white-listed", r.loc)
        # in-place alias: the mutated Tensor's pre-mutation id is read
        # later — the replay env serves the STALE value (eager saw the
        # mutated one)
        if r.name in inplace_targets and r.in_ids:
            tgt = r.in_ids[0]
            last = consumed_after.get(tgt, -1)
            fetched = tgt in fetch_set
            if last > i or fetched:
                report.add(
                    "TPU704", i, r.name,
                    f"in-place op mutates v{tgt} but its pre-mutation "
                    f"value is {'fetched' if fetched and last <= i else f'read by op#{last}'}"
                    f" — a replay serves the stale value", r.loc)
        for o in r.out_ids:
            produced[o] = i
        known.update(r.out_ids)
    if fetch_ids is not None:
        for fid in fetch_ids:
            if fid not in known:
                report.add("TPU705", -1, "<fetch>",
                           f"fetched value v{fid} is produced by no op "
                           f"and is neither a feed nor a captured "
                           f"parameter")
        used = set()
        for r in records:
            used.update(r.in_ids)
        alias_family = inplace_targets | _wf
        for i, r in enumerate(records):
            if r.name in _ARM_OPS + _LOOP_OPS:
                continue             # constructs may run for effect
            if (r.name in alias_family and r.in_ids
                    and (r.in_ids[0] in used
                         or r.in_ids[0] in fetch_set)):
                # a mutation IS the op's effect: consumers observe it
                # through the alias target's id after the payload swap
                continue
            if r.out_ids and not any(o in used or o in fetch_set
                                     for o in r.out_ids):
                report.add("TPU703", i, r.name,
                           "no output of this op is consumed or "
                           "fetched (dead op)", r.loc)


# ---------------------------------------------------------------------------
# Pass 2 — collective safety (TPU4xx)
# ---------------------------------------------------------------------------
def _branch_meta(r: Record):
    fn = r.fn
    meta = getattr(fn, "_verifier_branches", None) \
        if fn is not None else None
    if meta is None:
        meta = r.attrs.get("_verifier_branches")
    return meta


def _is_collective_entry(entry) -> bool:
    """A branch-trace entry is a collective only when it came through
    the collective layer's branch-trace seam, which always stamps the
    ``group`` attr — name membership alone would confuse the plain
    TENSOR op ``scatter`` (indexing) with the distributed primitive."""
    return (entry["name"] in COLLECTIVE_OPS
            and "group" in (entry.get("attrs") or {}))


def _coll_signature(entry) -> tuple:
    """(name, attrs, shape) identity of one traced collective — the
    full attr set the seam stamps (group/axes plus reduce op, src, …),
    i.e. the fields flight.diff_ranks compares across ranks."""
    attrs = entry.get("attrs") or {}
    return (entry["name"],
            tuple(sorted((k, v) for k, v in attrs.items())),
            tuple(entry.get("shape") or ()))


def _branch_collectives(ops, out):
    """Flatten one branch trace's collective sequence (nested
    constructs contribute their first arm — nested mismatches are
    flagged on their own construct)."""
    for entry in ops:
        if _is_collective_entry(entry):
            out.append(_coll_signature(entry))
        meta = entry.get("branches")
        if meta:
            branches = meta.get("branches") or []
            if meta.get("construct") in _LOOP_OPS:
                for b in branches:
                    _branch_collectives(b, out)
            elif branches:
                _branch_collectives(branches[0], out)
    return out


def _iter_constructs(ops):
    """Yield nested construct metas inside a branch trace."""
    for entry in ops:
        meta = entry.get("branches")
        if meta:
            yield meta


def _check_construct(meta, i, name, loc, report: Report):
    branches = meta.get("branches") or []
    construct = meta.get("construct", name)
    if construct in _LOOP_OPS:
        colls = []
        for b in branches:
            _branch_collectives(b, colls)
        if colls:
            names = sorted({c[0] for c in colls})
            report.add(
                "TPU401", i, name,
                f"collective(s) {names} execute under a data-dependent "
                f"loop — ranks whose predicates disagree run different "
                f"collective counts and desynchronize", loc)
    else:
        seqs = [_branch_collectives(b, []) for b in branches]
        if any(seqs):
            base = seqs[0]
            for bi, s in enumerate(seqs[1:], start=1):
                if [c[0] for c in s] != [c[0] for c in base]:
                    if sorted(c[0] for c in s) == \
                            sorted(c[0] for c in base):
                        report.add(
                            "TPU404", i, name,
                            f"branch 0 orders collectives "
                            f"{[c[0] for c in base]} but branch {bi} "
                            f"orders {[c[0] for c in s]} — ranks taking "
                            f"different arms cross-match transports",
                            loc)
                    else:
                        report.add(
                            "TPU402", i, name,
                            f"branch 0 traces collectives "
                            f"{[c[0] for c in base]} but branch {bi} "
                            f"traces {[c[0] for c in s]} — ranks taking "
                            f"different arms desynchronize", loc)
                    continue
                for k, (ca, cb) in enumerate(zip(base, s)):
                    if ca != cb:
                        report.add(
                            "TPU403", i, name,
                            f"collective #{k} ({ca[0]}) differs "
                            f"between branch 0 {ca[1:]} and branch "
                            f"{bi} {cb[1:]} (group/axes/shape must "
                            f"match for the transports to pair)", loc)
    # recurse into nested constructs of every arm
    for b in branches:
        for sub in _iter_constructs(b):
            _check_construct(sub, i, f"{name}/nested", loc, report)


def _collective_pass(records: List[Record], report: Report):
    for i, r in enumerate(records):
        if r.name not in _ARM_OPS + _LOOP_OPS:
            continue
        meta = _branch_meta(r)
        if meta is None:
            continue                 # pre-seam record: nothing to read
        _check_construct(meta, i, r.name, r.loc, report)


# ---------------------------------------------------------------------------
# Pass 3 — sharding / mesh pre-flight (TPU5xx)
# ---------------------------------------------------------------------------
#: op names that legitimately consume a Partial value (they ARE the
#: pending reduction)
_PARTIAL_RESOLVERS = frozenset({"all_reduce", "reduce_scatter",
                                "reduce", "mp_allreduce_sum"})


def _axes_product(mesh, axes) -> int:
    n = 1
    for a in (axes if isinstance(axes, tuple) else (axes,)):
        if a is None:
            continue
        n *= int(mesh.shape[a])
    return n


def _check_divisibility(spec, shape, mesh, where, i, name, loc,
                        report: Report, seen):
    if spec is None:
        return
    for d, (entry, size) in enumerate(zip(spec, shape)):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        factor = _axes_product(mesh, axes)
        if factor > 1 and int(size) % factor != 0:
            key = (i, where, d)
            if key in seen:
                continue
            seen.add(key)
            report.add(
                "TPU501", i, name,
                f"{where} dim {d} (size {size}) is sharded over mesh "
                f"axes {list(axes)} (x{factor}) but {size} % {factor} "
                f"!= 0 — the constraint will be silently dropped or "
                f"padded", loc)


def _sharding_pass(records, program, mesh, in_specs, param_specs,
                   fetch_ids, report: Report, plan=None):
    from ..distributed.spmd import rules as R
    from ..distributed.spmd import propagate as prop
    R.attach_spmd_rules()
    if plan is not None and len(plan.annotations) != len(records):
        plan = None                  # stale plan: recompute
    env: Dict[int, tuple] = {}
    partial_env: Dict[int, tuple] = {}
    seen_div = set()
    if program is not None:
        for fname, vid in program.feed_vars.items():
            shape = program._feed_shapes.get(fname, ())
            spec = R.normalize((in_specs or {}).get(fname), len(shape))
            env[vid] = spec
            _check_divisibility(spec, [abs(s) for s in shape], mesh,
                                f"feed {fname!r}", -1, "<feed>", "",
                                report, seen_div)
        for vid, t in program._captured.items():
            spec = R.normalize(
                prop.param_spec_of(t, param_specs), len(t.shape))
            env[vid] = spec
            _check_divisibility(spec, t.shape, mesh,
                                f"param {getattr(t, 'name', vid)!r}",
                                -1, "<param>", "", report, seen_div)
    elif isinstance(in_specs, dict):
        env.update(in_specs)         # records path: id -> spec seeds
    # forward propagation mirroring propagate_program, plus the checks
    hot = set(fetch_ids or ())
    # hot path = ancestors of fetched values (all ops when no fetches)
    producers: Dict[int, int] = {}
    for i, r in enumerate(records):
        for o in r.out_ids:
            producers[o] = i
    on_hot = [fetch_ids is None] * len(records)
    if fetch_ids is not None:
        work = list(hot)
        seen_v = set()
        while work:
            v = work.pop()
            if v in seen_v:
                continue
            seen_v.add(v)
            pi = producers.get(v)
            if pi is None:
                continue
            on_hot[pi] = True
            work.extend(records[pi].in_ids)
    fallbacks = {}
    for i, r in enumerate(records):
        in_shapes = r.in_shapes or tuple(() for _ in r.in_ids)
        out_shapes = r.out_shapes or tuple(() for _ in r.out_ids)
        if plan is not None:
            # reuse the caller's propagation (shard_program hands its
            # ShardingPlan in, so the pass never re-runs the rules)
            res, tier = plan.annotations[i], plan.annotations[i].tier
        else:
            ins = [env.get(v, (None,) * len(s))
                   for v, s in zip(r.in_ids, in_shapes)]
            res, tier = prop.apply_rule(r.name, ins, in_shapes,
                                        r.attrs, out_shapes)
        if tier == "replicate-warn" and on_hot[i]:
            fallbacks.setdefault(r.name, i)
        # Partial consumed without reduction
        for v in r.in_ids:
            pend = partial_env.get(v)
            if not pend:
                continue
            if r.name in _PARTIAL_RESOLVERS:
                continue
            if any(res.out_partial):
                continue             # still pending, tracked forward
            report.add(
                "TPU503", i, r.name,
                f"v{v} carries a pending reduction over mesh axes "
                f"{list(pend)} (Partial) but {r.name!r} consumes it "
                f"without reducing — the partial sums leak into the "
                f"result unless the partitioner resolves them "
                f"implicitly", r.loc)
        for v, spec, shape in zip(r.in_ids, res.in_specs, in_shapes):
            _check_divisibility(spec, shape, mesh, "input", i, r.name,
                                r.loc, report, seen_div)
        for v, spec, pend, shape in zip(
                r.out_ids, res.out_specs,
                res.out_partial + [()] * len(r.out_ids), out_shapes):
            env[v] = spec
            _check_divisibility(spec, shape, mesh, "output", i, r.name,
                                r.loc, report, seen_div)
            if pend:
                partial_env[v] = pend
    for name, i in sorted(fallbacks.items(), key=lambda kv: kv[1]):
        report.add(
            "TPU502", i, name,
            f"{name!r} has no sharding rule (named or category) and "
            f"sits on the hot path — its outputs replicate and every "
            f"downstream shard is lost", records[i].loc)
    if fetch_ids is not None:
        for fid in fetch_ids:
            pend = partial_env.get(fid)
            if pend:
                report.add(
                    "TPU503", producers.get(fid, -1), "<fetch>",
                    f"fetched value v{fid} is still Partial over mesh "
                    f"axes {list(pend)} — the caller receives "
                    f"unreduced partial sums")


# ---------------------------------------------------------------------------
# Pass 4 — donation hazards (TPU6xx)
# ---------------------------------------------------------------------------
def _donation_pass(host_reads, report: Report):
    for read in host_reads or ():
        report.add(
            "TPU601", int(read.get("pos", -1)),
            str(read.get("param", "<param>")),
            f"parameter {read.get('param')!r} is marked for donation "
            f"but the traced step host-reads it via "
            f"{read.get('site', 'a host read')} — after the donating "
            f"call that buffer no longer holds data",
            read.get("loc", ""))


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------
def check(program, mesh=None, in_specs=None, param_specs=None,
          fetch_ids=None, host_reads=(), label=None,
          contract=True, plan=None, memory=True, capacity_bytes=None,
          donated_ids=()) -> Report:
    """Verify a recorded program (or any op-record list).

    ``program``: a ``static.Program`` or a sequence of records carrying
    ``name/in_ids/out_ids/attrs/in_shapes/out_shapes`` (optionally
    dtypes + ``loc``). ``mesh``/``in_specs``/``param_specs`` arm the
    sharding pass (same arguments as ``spmd.shard_program``).
    ``fetch_ids`` are the externally visible value ids (enables the
    dead/unfetchable analysis). ``host_reads`` feeds the donation pass
    (see :func:`audit_step`). ``plan`` is an optional
    already-computed ``ShardingPlan`` for this exact record list —
    callers that propagate anyway (``spmd.shard_program``) hand it in
    so the sharding pass never re-runs the rules. ``memory`` arms the
    TPU9xx static liveness/peak-HBM pass (``capacity_bytes`` overrides
    the chip spec; default ``FLAGS_verifier_hbm_capacity`` falling back
    to ``perf.chip_hbm_bytes()``); ``donated_ids`` are value ids whose
    buffers a donating step consumes — they shorten residency in the
    memory pass and arm the TPU752 write-after-donate check. Returns a
    :class:`Report`; apply the flag policy with :func:`enforce`.
    """
    records, prog = _records_of(program)
    report = Report(label=label or ("Program" if prog is not None
                                    else "records"))
    known = set()
    if prog is not None:
        known.update(prog.feed_vars.values())
        known.update(prog._captured.keys())
    if isinstance(in_specs, dict) and prog is None:
        known.update(in_specs.keys())
    from . import liveness as _liveness
    if contract:
        _contract_pass(records, report, fetch_ids=fetch_ids,
                       known_ids=known)
        _liveness.alias_pass(records, report, fetch_ids=fetch_ids,
                             donated_ids=donated_ids)
    _collective_pass(records, report)
    if mesh is not None:
        _sharding_pass(records, prog, mesh, in_specs, param_specs,
                       fetch_ids, report, plan=plan)
    _donation_pass(host_reads, report)
    if memory:
        _liveness.memory_pass(
            prog if prog is not None else records, report,
            fetch_ids=fetch_ids, plan=plan, mesh=mesh,
            donated_ids=donated_ids, capacity_bytes=capacity_bytes)
    report.stats = {"ops": len(records),
                    "passes": ["contract" if contract else None,
                               "alias" if contract else None,
                               "collective",
                               "sharding" if mesh is not None else None,
                               "donation" if host_reads else None,
                               "memory" if memory else None]}
    return report


check_records = check


_SEND_NAMES = ("send", "isend")
_RECV_NAMES = ("recv", "irecv")


def check_stages(stage_records, label: str = "pipeline") -> Report:
    """Static cross-stage desync analysis (TPU8xx).

    ``stage_records``: one record list per pipeline stage, each with
    explicit ``send``/``recv`` boundary records carrying ``peer``
    (adjacent stage index), ``seq`` (transfer position), and the
    boundary value's shape/dtype (send: ``in_shapes``/``in_dtypes``,
    recv: ``out_shapes``/``out_dtypes``) — the shape
    ``distributed.pipeline.StagePartition.stage_records`` emits. Every
    stage's send sequence must match the next stage's recv sequence in
    count (TPU801), value shape/dtype (TPU802), and order/peer
    (TPU803) — a mismatch is the static form of the cross-rank hang
    ``flight.diff_ranks`` diagnoses at runtime.
    """
    stages = [[Record.of(r) for r in recs] for recs in stage_records]
    S = len(stages)
    report = Report(label=label)
    checked = 0
    for s, recs in enumerate(stages):
        for i, r in enumerate(recs):
            peer = r.attrs.get("peer")
            if r.name in _SEND_NAMES and peer != s + 1:
                report.add("TPU803", i, r.name,
                           f"stage {s} sends to peer {peer} — pipeline "
                           f"boundary transfers must target the "
                           f"adjacent stage {s + 1}", r.loc)
            elif r.name in _RECV_NAMES and peer != s - 1:
                report.add("TPU803", i, r.name,
                           f"stage {s} receives from peer {peer} — "
                           f"pipeline boundary transfers must come "
                           f"from the adjacent stage {s - 1}", r.loc)
    for s in range(S - 1):
        sends = [(i, r) for i, r in enumerate(stages[s])
                 if r.name in _SEND_NAMES
                 and r.attrs.get("peer") == s + 1]
        recvs = [(i, r) for i, r in enumerate(stages[s + 1])
                 if r.name in _RECV_NAMES
                 and r.attrs.get("peer") == s]
        if len(sends) != len(recvs):
            report.add(
                "TPU801", -1, f"stage{s}->stage{s + 1}",
                f"stage {s} sends {len(sends)} value(s) but stage "
                f"{s + 1} receives {len(recvs)} — the pipeline "
                f"deadlocks at this boundary")
        for k in range(min(len(sends), len(recvs))):
            si, snd = sends[k]
            ri, rcv = recvs[k]
            s_shape = snd.in_shapes[0] if snd.in_shapes else None
            s_dt = snd.in_dtypes[0] if snd.in_dtypes else None
            r_shape = rcv.out_shapes[0] if rcv.out_shapes else None
            r_dt = rcv.out_dtypes[0] if rcv.out_dtypes else None
            if s_shape != r_shape or s_dt != r_dt:
                report.add(
                    "TPU802", ri, rcv.name,
                    f"boundary {s}->{s + 1} position {k}: send is "
                    f"{s_dt}{list(s_shape or ())}, recv expects "
                    f"{r_dt}{list(r_shape or ())}", rcv.loc or snd.loc)
            if snd.attrs.get("seq", k) != rcv.attrs.get("seq", k):
                report.add(
                    "TPU803", ri, rcv.name,
                    f"boundary {s}->{s + 1} position {k}: send seq "
                    f"{snd.attrs.get('seq')} pairs with recv seq "
                    f"{rcv.attrs.get('seq')} — transfer order "
                    f"diverges between the stages", rcv.loc or snd.loc)
            checked += 1
    report.stats = {"stages": S, "boundary_values": checked,
                    "ops": sum(len(recs) for recs in stage_records),
                    "passes": ["stages"]}
    return report


def audit_step(fn, args=(), kwargs=None, donate_params=(), mesh=None,
               in_specs=None, param_specs=None, label=None) -> Report:
    """Trace ``fn(*args, **kwargs)`` eagerly into a fresh program and
    verify it — including the donation pass: host reads of any
    parameter in ``donate_params`` during the step are recorded via the
    ``core.donation`` watch seam and flagged TPU601.

    This is the offline complement of the ``to_static`` wiring (which
    watches the real jit trace); the planner uses the same
    trace-eagerly-once idiom."""
    from ..core import donation as _donation
    from ..core.tensor import Tensor
    from .program import Program, program_guard

    prog = Program()
    donate_params = list(donate_params)
    payload_to_param = {id(p._data): p for p in donate_params}
    host_reads: List[dict] = []

    def _watch(arr, site):
        p = payload_to_param.get(id(arr))
        if p is None:
            return
        host_reads.append({
            "param": getattr(p, "name", None) or f"param@{id(p)}",
            "site": site, "loc": user_loc(),
            "pos": len(prog.global_block().ops)})

    with program_guard(prog):
        with _donation.watch_reads(_watch):
            out = fn(*args, **(kwargs or {}))
    import jax
    leaves, _ = jax.tree_util.tree_flatten(
        out, is_leaf=lambda x: isinstance(x, Tensor))
    fetch_ids = [id(l) for l in leaves if isinstance(l, Tensor)]
    return check(prog, mesh=mesh, in_specs=in_specs,
                 param_specs=param_specs, fetch_ids=fetch_ids or None,
                 host_reads=host_reads,
                 label=label or getattr(fn, "__name__", "step"))


# ---------------------------------------------------------------------------
# Online scope: record + verify a to_static / Engine trace
# ---------------------------------------------------------------------------
class trace_scope:
    """Record every dispatched op during a jit trace (the same recorder
    seam the fusion pass and spmd propagation ride) and verify the
    stream when the trace completes.

    Used by ``jit/api.py``: enter before the first-call compile, call
    :meth:`note_donated` from ``jit_target`` once the params are
    rebound to tracers, and :meth:`finish` after a successful trace.
    On a graph break, :meth:`donation_report` surfaces any donated
    host-read recorded before the break."""

    def __init__(self, label="to_static", donate=False):
        self.label = label
        self.donate = donate
        self.records: List[Record] = []
        self.host_reads: List[dict] = []
        self._donated_payloads: Dict[int, object] = {}
        self._watch_token = None

    # -- dispatch recorder hook -------------------------------------------
    def _hook(self, op_name, f, tensor_inputs, out_tensors, attrs=None):
        self.records.append(Record(
            name=op_name, fn=f,
            in_ids=tuple(id(t) for t in tensor_inputs),
            out_ids=tuple(id(t) for t in out_tensors),
            attrs=attrs or {},
            in_shapes=tuple(tuple(t.shape) for t in tensor_inputs),
            out_shapes=tuple(tuple(t.shape) for t in out_tensors),
            in_dtypes=tuple(str(t.dtype) for t in tensor_inputs),
            out_dtypes=tuple(str(t.dtype) for t in out_tensors),
            loc=user_loc()))

    # -- donation watch ----------------------------------------------------
    def begin_trace(self, params=()):
        """Called at the top of the traced target, after params are
        rebound onto the trace's argument tracers: resets the record
        stream (jax may retrace the target) and notes the donated
        payloads — a host read of one of THESE during the trace is a
        donated-then-read hazard."""
        self.records = []
        self.host_reads = []
        if self.donate:
            self._donated_payloads = {
                id(p._data): (getattr(p, "name", None) or f"param#{i}")
                for i, p in enumerate(params)}
            # tensor-identity view of the same set: record in_ids carry
            # id(tensor), so the TPU752 write-after-donate and the
            # donation-shortened liveness intervals key on these
            self._donated_tids = tuple(id(p) for p in params)

    note_donated = begin_trace

    def _watch(self, arr, site):
        name = self._donated_payloads.get(id(arr))
        if name is None:
            return
        self.host_reads.append({
            "param": name, "site": site, "loc": user_loc(),
            "pos": len(self.records)})

    def __enter__(self):
        from ..core import dispatch
        from ..core import donation as _donation
        dispatch.register_recorder_hook(self._hook)
        if self.donate:
            self._watch_token = _donation.watch_reads(self._watch)
            self._watch_token.__enter__()
        return self

    def __exit__(self, *exc):
        from ..core import dispatch
        dispatch.unregister_recorder_hook(self._hook)
        if self._watch_token is not None:
            self._watch_token.__exit__(*exc)
            self._watch_token = None
        return False

    # -- verdicts ----------------------------------------------------------
    def finish(self) -> Report:
        """Verify the recorded stream (contract + collective passes —
        sharding constraints on this path are owned by the spmd
        trace_scope's own propagation) and apply the flag policy.
        Called at END OF TRACE, before lowering/compile. The record
        stream (op fns are closure-bearing) is dropped once the report
        is built so the scope retains nothing after the compile."""
        from . import crossrank as _crossrank
        _crossrank.maybe_dump(self.records, label=self.label)
        report = check(self.records, host_reads=self.host_reads,
                       label=self.label, fetch_ids=None,
                       donated_ids=getattr(self, "_donated_tids", ()))
        self.records = []
        self.host_reads = []
        self._donated_payloads = {}
        return enforce(report)

    def donation_report(self) -> Optional[Report]:
        """Report covering only the donated host-read hazards (the
        graph-break path: the trace died mid-stream, so contract
        analysis over the partial stream would be noise)."""
        if not self.host_reads:
            self.records = []
            return None
        report = Report(label=self.label)
        _donation_pass(self.host_reads, report)
        self.records = []
        self.host_reads = []
        self._donated_payloads = {}
        return report
