"""static.nn — layer helpers for construct-then-execute code
(reference python/paddle/static/nn/common.py fc, embedding)."""
from __future__ import annotations

from .. import nn as _nn


def fc(x, size, num_flatten_dims=1, activation=None, name=None,
       weight_attr=None, bias_attr=None):
    """Fully-connected over the flattened trailing dims (reference
    static/nn/common.py fc). Creates its parameters at build time; they
    are captured by the enclosing Program as weights. bias_attr=False
    drops the bias; other attrs pass through to the Linear layer."""
    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        in_features *= int(s)
    layer = _nn.Linear(in_features, size, weight_attr=weight_attr,
                       bias_attr=bias_attr)
    # flatten derives its shape from the runtime array, so the recorded
    # program stays batch-polymorphic (a reshape attr would freeze the
    # build-time example batch)
    h = x.flatten(start_axis=num_flatten_dims) if num_flatten_dims < len(
        x.shape) - 1 else x
    out = layer(h)
    if activation == "relu":
        from ..nn import functional as F
        out = F.relu(out)
    elif activation == "tanh":
        out = out.tanh()
    elif activation == "sigmoid":
        out = out.sigmoid()
    elif activation is not None:
        raise ValueError(f"unsupported activation {activation!r}")
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    """reference static/nn/common.py embedding. is_sparse is a gradient
    storage hint the SPMD design does not need; ``dtype`` selects the
    embedding weight dtype (float16/bfloat16/float32; float64 requires
    JAX_ENABLE_X64)."""
    import jax

    import numpy as _np

    from ..core import dtype as dtypes
    want = dtypes.convert_dtype(str(dtype).replace("paddle.", ""))
    if _np.dtype(want) == _np.float64 and not jax.config.jax_enable_x64:
        # jax silently truncates f64->f32 without x64 mode; a wrong-dtype
        # result must be an error, not a warning
        raise NotImplementedError(
            "static.nn.embedding: dtype='float64' requires "
            "JAX_ENABLE_X64=1 (jax would silently truncate to float32)")
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          weight_attr=param_attr)
    if layer.weight.dtype != want:
        layer.weight._swap_payload(layer.weight._data.astype(want))
    return layer(input)


__all__ = ["fc", "embedding"]
