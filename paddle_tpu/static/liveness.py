"""Static liveness & peak-HBM analysis over the recorded op-list IR.

The round-12 census measures HBM *after* allocation; this module is its
compile-time complement: per-value live intervals (def -> last use,
extended through in-place alias chains, donation-shortened, fetch-pinned)
over the same ``_OpRecord`` stream every compile path already records,
folded into a peak-HBM curve with per-op attribution — the memory half of
what Alpa-style planners compute statically before committing a placement.

Three consumers:

* **verifier (TPU9xx)** — :func:`memory_pass` compares the static peak
  against ``perf.chip_hbm_bytes()`` (or ``FLAGS_verifier_hbm_capacity``)
  and emits TPU901 (over capacity, error: strict mode raises before XLA
  ever sees the program) / TPU902 (>= 90%, warn).
* **verifier (TPU75x)** — :func:`alias_pass` extends the in-place
  staleness contract (TPU704) to the ``setitem`` / ``scatter_`` /
  ``index_put_`` / ``.at[].set`` write family with *region* precision:
  statically disjoint write/read regions are proven safe, overlapping
  ones are errors, data-dependent index writes are warned about, and
  writes through views / donated buffers get their own codes.
* **planner** — :func:`activation_peak` replaces cost.py's
  "every forward activation resident" estimate with true
  liveness-at-peak (sharding-aware via the round-13 ``ShardingPlan``).

:func:`measure_peak` is the drift guard: it replays a program eagerly on
real arrays under the *same* deletion schedule the static model assumes
and reports the measured high-water (feeding the census phase gauges),
so a tier-1 test can assert the static size model tracks real buffers.

Sizing contract: a value's bytes are ``numel * dtype_bytes`` of its
*recorded* shape/dtype, scaled by its shard fraction when a
``ShardingPlan`` is supplied. In-place chains count BOTH buffers (the
pre-mutation value until its last reader, the new value through the
alias target's lifetime) — the conservative model matching eager
payload-swap semantics, where both arrays coexist until the old one's
last reference dies.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..core import flags as _flags
from ..observability.perf.costmodel import dtype_bytes
from .verifier import Record, _records_of

__all__ = [
    "Interval", "LivenessResult", "analyze", "peak_report",
    "render_peak_report", "memory_pass", "alias_pass", "activation_peak",
    "measure_peak", "stage_peaks", "WRITE_FAMILY", "VIEW_OPS",
]

_flags.define_flag(
    "verifier_hbm_capacity", 0,
    "Override chip HBM bytes for the static memory pass (TPU901/902); "
    "0 = perf.chip_hbm_bytes() of the attached device.")

#: region-writing ops the alias pass (TPU75x) owns — excluded from the
#: generic in-place staleness check (TPU704), which has no region
#: precision and would double-flag provably-disjoint rewrites
WRITE_FAMILY = frozenset({
    "setitem", "scatter_", "index_put_", "index_add_", "index_fill_",
    "masked_fill_", "masked_scatter_",
})

#: ops whose output is a VIEW of input 0 under reference (torch/paddle)
#: semantics — on XLA every array is functional, so an in-place write
#: through one of these silently diverges from the reference: the base
#: is never updated
VIEW_OPS = frozenset({
    "getitem", "slice", "reshape", "view", "transpose", "squeeze",
    "unsqueeze", "flatten", "expand", "split", "chunk",
})

#: generic (whole-buffer) in-place ops: registry inplace variants plus
#: the torch-compat ``INPLACE_OF`` table, minus the region write family
def _inplace_names() -> set:
    from ..ops.registry import OPS
    names = {d.inplace_variant for d in OPS.values() if d.inplace_variant}
    try:
        from ..ops.inplace import INPLACE_OF
        names.update(INPLACE_OF)
    except Exception:                 # pragma: no cover - partial import
        pass
    return names


class Interval:
    """One value's residency: op index of def (-1 = live at entry) to op
    index of last use (``n_ops`` = pinned through program end)."""

    __slots__ = ("vid", "start", "end", "nbytes", "origin", "label",
                 "shape", "dtype")

    def __init__(self, vid, start, end, nbytes, origin, label, shape,
                 dtype):
        self.vid = vid
        self.start = start
        self.end = end
        self.nbytes = float(nbytes)
        self.origin = origin          # "feed" | "param" | "op"
        self.label = label            # feed name / param name / op name
        self.shape = tuple(shape)
        self.dtype = dtype


class LivenessResult:
    __slots__ = ("intervals", "curve", "peak_bytes", "peak_index",
                 "n_ops", "entry_bytes", "records")

    def __init__(self, intervals, curve, peak_bytes, peak_index, n_ops,
                 entry_bytes, records):
        self.intervals: Dict[int, Interval] = intervals
        self.curve: List[float] = curve
        self.peak_bytes = peak_bytes
        self.peak_index = peak_index
        self.n_ops = n_ops
        self.entry_bytes = entry_bytes
        self.records: List[Record] = records

    def live_at(self, i: int) -> List[Interval]:
        return [iv for iv in self.intervals.values()
                if iv.start <= i <= iv.end]


def _numel(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _shard_frac(spec, mesh, shape) -> float:
    if spec is None or mesh is None:
        return 1.0
    from ..distributed.planner.cost import shard_fraction
    try:
        return shard_fraction(spec, mesh, shape)
    except Exception:                 # pragma: no cover - malformed spec
        return 1.0


def _value_nbytes(shape, dtype, spec=None, mesh=None) -> float:
    try:
        item = dtype_bytes(dtype) if dtype else 4
    except Exception:
        item = 4
    return _numel(shape) * item * _shard_frac(spec, mesh, shape)


def analyze(program, fetch_ids=None, plan=None, mesh=None,
            donated_ids=()) -> LivenessResult:
    """Live intervals + peak-HBM curve for a ``static.Program`` or any
    op-record sequence.

    * feeds / captured params are resident for the whole program
      (caller-held buffers) — unless their id is in ``donated_ids``, in
      which case donation frees them after their last use (the round-17
      donation contract).
    * op outputs live from their def to their last use; fetched values
      are pinned through program end.
    * in-place alias chains (generic in-place ops AND the TPU75x write
      family): the new value's buffer is extended through the alias
      target's lifetime — eager payload-swap keeps it reachable via the
      target's Python identity.
    * sizes are sharding-aware when ``plan`` (a ``ShardingPlan``) and
      ``mesh`` are given: each value is scaled by its shard fraction
      from ``plan.env``.
    """
    records, prog = _records_of(program)
    n = len(records)
    fetch_set = set(fetch_ids or ())
    donated = set(donated_ids or ())
    env = getattr(plan, "env", None) or {}
    pmesh = mesh if mesh is not None else getattr(plan, "mesh", None)

    produced_at: Dict[int, int] = {}
    last_use: Dict[int, int] = {}
    meta: Dict[int, tuple] = {}       # vid -> (shape, dtype)
    entry_order: List[int] = []
    for i, r in enumerate(records):
        for k, v in enumerate(r.in_ids):
            last_use[v] = i
            if v not in produced_at and v not in meta:
                entry_order.append(v)
            if v not in meta:
                shape = r.in_shapes[k] if k < len(r.in_shapes) else ()
                dt = r.in_dtypes[k] if k < len(r.in_dtypes) else ""
                meta[v] = (shape, dt)
        for k, v in enumerate(r.out_ids):
            if v not in produced_at:
                produced_at[v] = i
            shape = r.out_shapes[k] if k < len(r.out_shapes) else ()
            dt = r.out_dtypes[k] if k < len(r.out_dtypes) else ""
            meta[v] = (shape, dt)

    feeds: Dict[int, str] = {}
    caps: Dict[int, str] = {}
    if prog is not None:
        for name, vid in prog.feed_vars.items():
            feeds[vid] = name
            if vid not in meta:
                meta[vid] = (prog._feed_shapes.get(name, ()),
                             prog._feed_dtypes.get(name, ""))
        for vid, t in prog._captured.items():
            caps[vid] = getattr(t, "name", None) or f"param:v{vid}"
            if vid not in meta:
                meta[vid] = (tuple(getattr(t, "shape", ())),
                             str(getattr(t, "dtype", "")))

    intervals: Dict[int, Interval] = {}

    def entry_interval(vid, origin, label):
        shape, dt = meta.get(vid, ((), ""))
        end = n
        if vid in donated:
            end = last_use.get(vid, -1)
        intervals[vid] = Interval(
            vid, -1, end,
            _value_nbytes(shape, dt, env.get(vid), pmesh),
            origin, label, shape, dt)

    for vid, name in feeds.items():
        entry_interval(vid, "feed", name)
    for vid, name in caps.items():
        if vid not in intervals:
            entry_interval(vid, "param", name)
    for vid in entry_order:
        # record-list entry values (unproduced inputs) are implicit
        # parameters
        if vid not in intervals and vid not in produced_at:
            entry_interval(vid, "param", f"param:v{vid}")

    for i, r in enumerate(records):
        for k, vid in enumerate(r.out_ids):
            if vid in intervals or produced_at.get(vid) != i:
                continue
            shape, dt = meta[vid]
            end = n if vid in fetch_set else last_use.get(vid, i)
            intervals[vid] = Interval(
                vid, i, end,
                _value_nbytes(shape, dt, env.get(vid), pmesh),
                "op", r.name, shape, dt)

    # alias extension, forward order so def-ordered chains propagate:
    # the in-place result's buffer stays reachable through the mutated
    # tensor's identity until THAT value dies
    alias_names = _inplace_names() | WRITE_FAMILY
    for i, r in enumerate(records):
        if r.name not in alias_names or not r.in_ids or not r.out_ids:
            continue
        tgt = intervals.get(r.in_ids[0])
        out = intervals.get(r.out_ids[0])
        if tgt is not None and out is not None and tgt.end > out.end:
            out.end = tgt.end

    entry_bytes = sum(iv.nbytes for iv in intervals.values()
                      if iv.start < 0)
    if n == 0:
        return LivenessResult(intervals, [entry_bytes], entry_bytes, 0,
                              0, entry_bytes, records)

    delta = [0.0] * (n + 1)
    for iv in intervals.values():
        s = max(iv.start, 0)
        e = min(iv.end, n - 1)
        if e < s:
            e = s                      # dead value: resident for its op
        delta[s] += iv.nbytes
        delta[e + 1] -= iv.nbytes
    curve: List[float] = []
    acc = 0.0
    for i in range(n):
        acc += delta[i]
        curve.append(acc)
    peak_index = max(range(n), key=curve.__getitem__)
    return LivenessResult(intervals, curve, curve[peak_index],
                          peak_index, n, entry_bytes, records)


# ---------------------------------------------------------------------------
# peak report (per-op attribution)
# ---------------------------------------------------------------------------
def peak_report(program, fetch_ids=None, plan=None, mesh=None,
                donated_ids=(), top_k=5, capacity_bytes=None) -> dict:
    """Name the op at the high-water mark and the top-k live values.

    Returns ``{"peak_bytes", "peak_index", "peak_op": {name, loc},
    "top_values": [...], "capacity_bytes", "utilization", "curve"}`` —
    the static complement of ``perf.memory.high_water``.
    """
    res = analyze(program, fetch_ids=fetch_ids, plan=plan, mesh=mesh,
                  donated_ids=donated_ids)
    cap = _capacity(capacity_bytes)
    if res.n_ops:
        r = res.records[res.peak_index]
        peak_op = {"index": res.peak_index, "name": r.name,
                   "loc": r.loc}
    else:
        peak_op = {"index": -1, "name": "<entry>", "loc": ""}
    live = sorted(res.live_at(res.peak_index) if res.n_ops else
                  res.intervals.values(),
                  key=lambda iv: -iv.nbytes)
    top = [{
        "vid": iv.vid, "nbytes": iv.nbytes, "origin": iv.origin,
        "label": iv.label, "shape": iv.shape, "dtype": iv.dtype,
        "def": iv.start, "last_use": iv.end,
    } for iv in live[:max(0, int(top_k))]]
    return {
        "peak_bytes": res.peak_bytes,
        "peak_index": res.peak_index,
        "peak_op": peak_op,
        "n_ops": res.n_ops,
        "entry_bytes": res.entry_bytes,
        "top_values": top,
        "capacity_bytes": cap,
        "utilization": (res.peak_bytes / cap) if cap else 0.0,
        "curve": res.curve,
    }


def render_peak_report(rep: dict) -> str:
    gib = 1024.0 ** 3
    lines = [
        "static peak HBM: %.3f GiB at op#%d %s (%s) — %.1f%% of "
        "%.1f GiB capacity" % (
            rep["peak_bytes"] / gib, rep["peak_op"]["index"],
            rep["peak_op"]["name"], rep["peak_op"]["loc"] or "?",
            100.0 * rep["utilization"],
            (rep["capacity_bytes"] or 0) / gib)]
    for tv in rep["top_values"]:
        lines.append(
            "  %10.1f MiB  %-6s %-24s %s %s [op#%d..%s]" % (
                tv["nbytes"] / 1024.0 ** 2, tv["origin"],
                str(tv["label"])[:24], tv["shape"], tv["dtype"],
                tv["def"], tv["last_use"]))
    return "\n".join(lines)


def _capacity(capacity_bytes=None) -> float:
    if capacity_bytes:
        return float(capacity_bytes)
    flag = _flags.get_flag("verifier_hbm_capacity")
    if flag:
        return float(flag)
    try:
        from ..observability import perf as _perf
        return float(_perf.chip_hbm_bytes())
    except Exception:                 # pragma: no cover - no device
        return 16e9


# ---------------------------------------------------------------------------
# verifier pass: TPU9xx over-capacity
# ---------------------------------------------------------------------------
def memory_pass(program, report, *, fetch_ids=None, plan=None,
                mesh=None, donated_ids=(), capacity_bytes=None):
    """Emit TPU901 (static peak > chip HBM, error) / TPU902 (>= 90%,
    warn) into ``report`` — raised in strict mode before XLA compiles."""
    cap = _capacity(capacity_bytes)
    if not cap:
        return None
    res = analyze(program, fetch_ids=fetch_ids, plan=plan, mesh=mesh,
                  donated_ids=donated_ids)
    if res.peak_bytes <= 0.9 * cap:
        return res
    gib = 1024.0 ** 3
    top = sorted(res.live_at(res.peak_index), key=lambda iv: -iv.nbytes)
    head = ", ".join(
        "%s %s %.2f GiB" % (iv.label, iv.shape, iv.nbytes / gib)
        for iv in top[:3])
    i = res.peak_index
    r = res.records[i] if res.n_ops else None
    name = r.name if r is not None else "<entry>"
    loc = r.loc if r is not None else ""
    if res.peak_bytes > cap:
        report.add(
            "TPU901", i, name,
            "static peak HBM %.2f GiB exceeds chip capacity %.2f GiB "
            "at op#%d %s — largest live values: %s" % (
                res.peak_bytes / gib, cap / gib, i, name, head), loc)
    else:
        report.add(
            "TPU902", i, name,
            "static peak HBM %.2f GiB is %.0f%% of chip capacity "
            "%.2f GiB — largest live values: %s" % (
                res.peak_bytes / gib, 100.0 * res.peak_bytes / cap,
                cap / gib, head), loc)
    return res


# ---------------------------------------------------------------------------
# verifier pass: TPU75x setitem/scatter alias checking
# ---------------------------------------------------------------------------
def _region_of(attrs, key):
    reg = (attrs or {}).get(key)
    if not reg:
        return None
    try:
        return tuple((int(s), int(e)) for s, e in reg)
    except Exception:
        return None


def _regions_disjoint(wr, rr) -> bool:
    """True only when the two static regions PROVABLY do not overlap:
    some dimension's [start, stop) intervals are disjoint. Dims beyond a
    region's recorded prefix are full-extent (always overlapping)."""
    if wr is None or rr is None:
        return False
    for k in range(min(len(wr), len(rr))):
        (ws, we), (rs, re) = wr[k], rr[k]
        if we <= rs or re <= ws:
            return True
    return False


def alias_pass(program, report, *, fetch_ids=None, donated_ids=()):
    """Region-precise staleness contract for the write family.

    * TPU751 (error): a later op reads the pre-write value of a mutated
      tensor and the written region provably overlaps the read — the
      replay env serves the stale pre-mutation buffer.
    * TPU752 (error): write through a donated buffer — the payload the
      write adopts was already handed to XLA.
    * TPU753 (warn): write through a VIEW whose base is still read —
      functional XLA arrays never propagate the write to the base
      (silent divergence from reference in-place view semantics).
    * TPU754 (warn): data-dependent (tensor) indices make the written
      region unprovable while the pre-write value is still read.
    """
    records, _prog = _records_of(program)
    fetch_set = set(fetch_ids or ())
    donated = set(donated_ids or ())
    producer: Dict[int, Record] = {}
    producer_idx: Dict[int, int] = {}
    for i, r in enumerate(records):
        for v in r.out_ids:
            if v not in producer:
                producer[v] = r
                producer_idx[v] = i
    for i, r in enumerate(records):
        if r.name not in WRITE_FAMILY or not r.in_ids:
            continue
        tgt = r.in_ids[0]
        wr = _region_of(r.attrs, "write_region")

        if tgt in donated:
            report.add(
                "TPU752", i, r.name,
                f"write into donated buffer v{tgt} — the buffer was "
                f"donated to the compiled step and no longer backs "
                f"this value", r.loc)

        src = producer.get(tgt)
        if src is not None and src.name in VIEW_OPS and src.in_ids:
            base = src.in_ids[0]
            base_read_later = any(
                base in s.in_ids for s in records[i + 1:]) \
                or base in fetch_set
            if base_read_later:
                report.add(
                    "TPU753", i, r.name,
                    f"in-place write through view v{tgt} (a "
                    f"{src.name!r} of v{base}) — XLA arrays are "
                    f"functional, the base is NEVER updated; later "
                    f"reads of v{base} silently diverge from "
                    f"reference in-place semantics", r.loc)

        # later reads of the PRE-write value
        flagged = False
        for j in range(i + 1, len(records)):
            s = records[j]
            if tgt not in s.in_ids or flagged:
                continue
            rr = None
            if s.name == "getitem" and s.in_ids[0] == tgt:
                rr = _region_of(s.attrs, "read_region")
            if wr is not None and rr is not None \
                    and _regions_disjoint(wr, rr):
                continue               # provably disjoint: safe rewrite
            if wr is not None:
                report.add(
                    "TPU751", i, r.name,
                    f"op#{j} {s.name} reads v{tgt} after this write "
                    f"overwrote region {wr} — the replay env serves "
                    f"the stale pre-write value", r.loc)
            else:
                report.add(
                    "TPU754", i, r.name,
                    f"write region of v{tgt} is data-dependent "
                    f"(tensor indices) and op#{j} {s.name} reads the "
                    f"pre-write value — overlap cannot be proven "
                    f"statically", r.loc)
            flagged = True
        if not flagged and tgt in fetch_set:
            if wr is not None:
                report.add(
                    "TPU751", i, r.name,
                    f"v{tgt} is fetched after this write overwrote "
                    f"region {wr} — the fetch serves the stale "
                    f"pre-write value", r.loc)
            else:
                report.add(
                    "TPU754", i, r.name,
                    f"write region of v{tgt} is data-dependent "
                    f"(tensor indices) and the pre-write value is "
                    f"fetched — overlap cannot be proven statically",
                    r.loc)


# ---------------------------------------------------------------------------
# planner: liveness-at-peak activation pricing
# ---------------------------------------------------------------------------
def activation_peak(records, *, exclude_ids=(), plan=None, mesh=None,
                    fetch_ids=None, pinned_ids=()):
    """Peak simultaneously-live bytes of OP-PRODUCED values (params and
    feeds in ``exclude_ids`` are priced separately by the cost model).

    ``pinned_ids``: values held to program end regardless of last use —
    the cost model pins GEMM operands (saved for the backward wgrad).
    Returns ``(peak_bytes, peak_index, op_name)``.
    """
    recs = [Record.of(r) for r in records]
    res = analyze(recs, fetch_ids=fetch_ids, plan=plan, mesh=mesh)
    n = res.n_ops
    excl = set(exclude_ids or ())
    pinned = set(pinned_ids or ())
    if n == 0:
        return 0.0, 0, ""
    delta = [0.0] * (n + 1)
    for iv in res.intervals.values():
        if iv.start < 0 or iv.vid in excl:
            continue                   # entry value: priced elsewhere
        s = iv.start
        e = n - 1 if iv.vid in pinned else min(iv.end, n - 1)
        if e < s:
            e = s
        delta[s] += iv.nbytes
        delta[e + 1] -= iv.nbytes
    acc, best, best_i = 0.0, 0.0, 0
    for i in range(n):
        acc += delta[i]
        if acc > best:
            best, best_i = acc, i
    return best, best_i, recs[best_i].name


# ---------------------------------------------------------------------------
# pipeline: stage-aware peaks
# ---------------------------------------------------------------------------
def stage_peaks(stage_records, inflight=None, plan=None, mesh=None):
    """Per-stage static peaks with the schedule's peak-inflight
    microbatch count multiplying the ACTIVATION share (weights are
    resident once regardless of how many microbatches are in flight).

    ``stage_records``: the per-stage record lists
    ``StagePartition.stage_records()`` emits; ``inflight``: per-stage
    peak in-flight microbatches (int or list), default 1.
    """
    out = []
    for si, recs in enumerate(stage_records):
        res = analyze(list(recs), plan=plan, mesh=mesh)
        fl = inflight[si] if isinstance(inflight, (list, tuple)) \
            else (inflight or 1)
        activ = max(0.0, res.peak_bytes - res.entry_bytes)
        out.append({
            "stage": si,
            "peak_bytes": res.entry_bytes + float(fl) * activ,
            "one_shot_peak_bytes": res.peak_bytes,
            "entry_bytes": res.entry_bytes,
            "inflight": int(fl),
            "peak_index": res.peak_index,
        })
    return out


# ---------------------------------------------------------------------------
# measured cross-check (census drift guard)
# ---------------------------------------------------------------------------
def measure_peak(program, feed=None, fetch_ids=None, phase=None):
    """Replay ``program`` eagerly on real arrays under the SAME deletion
    schedule :func:`analyze` assumes (each value freed after its
    alias-extended last use) and report the measured live-byte
    high-water. With ``phase`` set, ``perf.memory.update_high_water`` is
    driven at every step so the census phase gauges record the same
    peak. The drift between this and ``analyze().peak_bytes`` is the
    size-model error a tier-1 test bounds.
    """
    import jax.numpy as jnp
    import numpy as np

    records, prog = _records_of(program)
    if prog is None:
        raise TypeError("measure_peak needs a static.Program (feeds + "
                        "captured params carry the entry arrays)")
    res = analyze(prog, fetch_ids=fetch_ids)
    if phase is not None:
        from ..observability.perf import memory as _mem

    env: Dict[int, object] = {}
    for name in sorted(prog.feed_vars):
        vid = prog.feed_vars[name]
        if feed is not None and name in feed:
            env[vid] = jnp.asarray(feed[name])
        else:
            shape = prog._feed_shapes.get(name, ())
            dt = prog._feed_dtypes.get(name, "float32") or "float32"
            shape = tuple(abs(int(d)) or 1 for d in shape)
            env[vid] = jnp.zeros(shape, dtype=np.dtype(dt))
    for vid, t in prog._captured.items():
        env[vid] = t._data

    def nbytes(a):
        return int(getattr(a, "nbytes", 0) or 0)

    free_at: Dict[int, List[int]] = {}
    for iv in res.intervals.values():
        if iv.start < 0:
            continue                   # entry buffers are caller-held
        free_at.setdefault(min(iv.end, res.n_ops - 1), []).append(iv.vid)

    entry_bytes = sum(nbytes(a) for a in env.values())
    live = entry_bytes
    peak, peak_i = live, -1
    floor = None
    if phase is not None:
        floor = _mem.census()["total"]
    for i, r in enumerate(records):
        args = [env[v] for v in r.in_ids]
        out = r.fn(*args) if r.fn is not None else None
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        # measure_peak IS the host-side drift guard: it deliberately
        # replays eagerly on concrete buffers and reads their sizes —
        # host sync is the measurement, not an accident
        for vid, a in zip(r.out_ids, outs):
            if vid not in env and a is not None:  # tpulint: disable=TPU105 — host replay loop, not a traced program
                env[vid] = a  # tpulint: disable=TPU203 — keyed on int value-ids, never tensors
                live += nbytes(a)
        if live > peak:  # tpulint: disable=TPU105 — live/peak are host ints
            peak, peak_i = live, i
        if phase is not None:
            _mem.update_high_water(phase)
        for vid in free_at.get(i, ()):
            a = env.pop(vid, None)
            if a is not None:
                live -= nbytes(a)
    out = {
        "peak_bytes": float(peak),   # tpulint: disable=TPU103 — sizes are host ints (nbytes), never device values
        "peak_index": peak_i,
        "entry_bytes": float(entry_bytes),  # tpulint: disable=TPU103 — host int accumulator
        "static_peak_bytes": res.peak_bytes,
        "static_peak_index": res.peak_index,
    }
    if phase is not None:
        out["census_floor"] = floor
        out["census_high_water"] = _mem.high_water(phase)["total"]
    return out
