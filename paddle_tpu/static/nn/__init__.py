"""static.nn — build-time layers + in-graph control flow.

Package mirrors the reference layout (python/paddle/static/nn/):
``common`` holds the construct-then-execute layer helpers, ``control_flow``
the data-dependent ``cond`` / ``while_loop`` / ``case`` / ``switch_case``
ops that lower to ``lax`` and compile INTO the captured program.
"""
from __future__ import annotations

from . import common, control_flow  # noqa: F401
from .common import *  # noqa: F401,F403
from .common import __all__ as _common_all
from .control_flow import case, cond, switch_case, while_loop  # noqa: F401

__all__ = list(_common_all) + ["cond", "while_loop", "case", "switch_case"]


def __getattr__(name):
    # the pre-package module exposed its private state (_SPARSE_EMB_AUTO
    # counter, _GEO_LAYERS registry, ...) as static.nn attributes; keep
    # that surface by forwarding unknown reads to the live common module
    return getattr(common, name)
