"""static.nn — layer helpers for construct-then-execute code
(reference python/paddle/static/nn/common.py fc, embedding)."""
from __future__ import annotations

from ... import nn as _nn


def fc(x, size, num_flatten_dims=1, activation=None, name=None,
       weight_attr=None, bias_attr=None):
    """Fully-connected over the flattened trailing dims (reference
    static/nn/common.py fc). Creates its parameters at build time; they
    are captured by the enclosing Program as weights. bias_attr=False
    drops the bias; other attrs pass through to the Linear layer."""
    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        in_features *= int(s)
    layer = _nn.Linear(in_features, size, weight_attr=weight_attr,
                       bias_attr=bias_attr)
    # flatten derives its shape from the runtime array, so the recorded
    # program stays batch-polymorphic (a reshape attr would freeze the
    # build-time example batch)
    h = x.flatten(start_axis=num_flatten_dims) if num_flatten_dims < len(
        x.shape) - 1 else x
    out = layer(h)
    if activation == "relu":
        from ...nn import functional as F
        out = F.relu(out)
    elif activation == "tanh":
        out = out.tanh()
    elif activation == "sigmoid":
        out = out.sigmoid()
    elif activation is not None:
        raise ValueError(f"unsupported activation {activation!r}")
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    """reference static/nn/common.py embedding. is_sparse is a gradient
    storage hint the SPMD design does not need; ``dtype`` selects the
    embedding weight dtype (float16/bfloat16/float32; float64 requires
    JAX_ENABLE_X64)."""
    import jax

    import numpy as _np

    from ...core import dtype as dtypes
    want = dtypes.convert_dtype(str(dtype).replace("paddle.", ""))
    if _np.dtype(want) == _np.float64 and not jax.config.jax_enable_x64:
        # jax silently truncates f64->f32 without x64 mode; a wrong-dtype
        # result must be an error, not a warning
        raise NotImplementedError(
            "static.nn.embedding: dtype='float64' requires "
            "JAX_ENABLE_X64=1 (jax would silently truncate to float32)")
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          weight_attr=param_attr)
    if layer.weight.dtype != want:
        layer.weight._swap_payload(layer.weight._data.astype(want))
    return layer(input)


_GEO_LAYERS = {}
_SPARSE_EMB_AUTO = 0


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """PS-mode embedding lookup (reference static/nn/common.py:3691
    ``sparse_embedding`` — the large-scale-sparse replacement for
    ``embedding`` under the parameter-server runtime).

    Requires PS mode (``fleet.init`` with a non-collective role maker);
    the table lives on the parameter servers, keyed by a table id hashed
    from the parameter name. ``size[0]`` (vocab rows) is advisory — PS
    tables grow lazily (reference MemorySparseTable entry semantics);
    ``table_class='MemorySparseGeoTable'`` selects the geo-SGD table.
    ``entry``/``slot`` (CTR feature admission plumbing) are accepted for
    signature parity and ignored, like ``is_sparse`` in ``embedding``.
    """
    import zlib

    from ...distributed.ps import _current_client, sparse_embedding_lookup
    from ...distributed.ps.embedding import GeoDistributedEmbedding

    name = (param_attr if isinstance(param_attr, str)
            else getattr(param_attr, "name", None))
    if not name:
        # auto-name like the reference's unique_name.generate: two unnamed
        # tables must NOT hash to one table id (silent weight sharing)
        global _SPARSE_EMB_AUTO
        name = f"sparse_embedding_{_SPARSE_EMB_AUTO}"
        _SPARSE_EMB_AUTO += 1
    table_id = zlib.adler32(name.encode()) % (1 << 30)
    client = _current_client()
    dim = int(size[1])
    if table_class == "MemorySparseGeoTable":
        # geo replicas are stateful: successive calls on the same param
        # name must share one local replica + delta bank
        key = (id(client), table_id)
        layer = _GEO_LAYERS.get(key)
        if layer is None:
            layer = GeoDistributedEmbedding(table_id, dim, client=client)
            _GEO_LAYERS[key] = layer
        layer.trainable = not is_test
        return layer(input)
    client.create_table(table_id, {"type": "sparse", "dim": dim,
                                   "accessor": "sgd"})
    return sparse_embedding_lookup(input, client, table_id, dim,
                                   trainable=not is_test)


def _act(out, activation):
    if activation is None:
        return out
    from ...nn import functional as F
    fn = getattr(F, activation, None)
    if fn is None:
        raise ValueError(f"unsupported activation {activation!r}")
    return fn(out)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, data_format="NCHW", name=None):
    """reference static/nn/common.py conv2d:779 — build-time Conv2D whose
    weights are captured by the enclosing Program."""
    in_channels = int(input.shape[1 if data_format == "NCHW" else -1])
    layer = _nn.Conv2D(in_channels, num_filters, filter_size,
                       stride=stride, padding=padding, dilation=dilation,
                       groups=groups, weight_attr=param_attr,
                       bias_attr=bias_attr, data_format=data_format)
    return _act(layer(input), act)


def batch_norm(input, act=None, is_test=False, momentum=0.9,
               epsilon=1e-5, param_attr=None, bias_attr=None,
               data_layout="NCHW", name=None):
    """reference static/nn/common.py batch_norm:2616. ``is_test`` freezes
    the running statistics (eval mode)."""
    num_channels = int(input.shape[1 if data_layout == "NCHW" else -1])
    layer = _nn.BatchNorm(num_channels, momentum=momentum,
                          epsilon=epsilon, weight_attr=param_attr,
                          bias_attr=bias_attr, data_format=data_layout)
    if is_test:
        layer.eval()
    return _act(layer(input), act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """reference static/nn/common.py layer_norm:3555 — normalizes over
    dims [begin_norm_axis:]."""
    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    layer = _nn.LayerNorm(shape, epsilon=epsilon,
                          weight_attr=param_attr if scale else False,
                          bias_attr=bias_attr if shift else False)
    return _act(layer(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    """reference static/nn/common.py instance_norm:271 (NCHW)."""
    layer = _nn.InstanceNorm2D(int(input.shape[1]), epsilon=epsilon,
                               weight_attr=param_attr, bias_attr=bias_attr)
    return layer(input)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference static/nn/common.py spectral_norm:3417 — normalizes a
    weight by its largest singular value (power iteration)."""
    layer = _nn.SpectralNorm(list(weight.shape), dim=dim,
                             power_iters=power_iters, epsilon=eps)
    return layer(weight)


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """reference static/nn/common.py py_func:3118 — run host Python inside
    the graph. TPU-native: ``jax.pure_callback`` (XLA host-callback op),
    so the call survives jit/program capture. ``out`` is a template
    Tensor (or list) carrying the result shapes/dtypes; ``backward_func``
    receives ONLY the upstream output gradients (one per output, in
    order) and returns one gradient per input — unlike the reference it
    is NOT handed the forward inputs/outputs, so
    ``skip_vars_in_backward_input`` has nothing to skip and is rejected
    rather than silently ignored (close over forward values instead)."""
    if skip_vars_in_backward_input is not None:
        raise NotImplementedError(
            "py_func: backward_func here receives only the upstream "
            "output gradients; skip_vars_in_backward_input is not "
            "applicable — close over any forward values you need")
    import jax
    import jax.numpy as jnp
    import numpy as _np

    from ...core import dispatch as _dispatch
    from ...core.tensor import Tensor as _T

    xs = x if isinstance(x, (list, tuple)) else [x]
    xs = [_T(v) if not isinstance(v, _T) else v for v in xs]
    outs = out if isinstance(out, (list, tuple)) else [out]
    specs = [jax.ShapeDtypeStruct(tuple(o.shape), _np.dtype(o.dtype))
             for o in outs]
    multi = isinstance(out, (list, tuple))

    def _np_call(fn, templates, *arrays):
        res = fn(*[_np.asarray(a) for a in arrays])
        rs = res if isinstance(res, (list, tuple)) else [res]
        return tuple(_np.asarray(r, dtype=t.dtype).reshape(t.shape)
                     for r, t in zip(rs, templates))

    def f(*arrays):
        res = jax.pure_callback(
            lambda *a: _np_call(func, specs, *a), tuple(specs), *arrays)
        return list(res) if multi else res[0]

    if backward_func is not None:
        in_specs = [jax.ShapeDtypeStruct(tuple(v.shape),
                                         _np.dtype(v.dtype)) for v in xs]
        fwd = f

        @jax.custom_vjp
        def f(*arrays):
            return fwd(*arrays)

        def _fwd(*arrays):
            return fwd(*arrays), arrays

        def _bwd(arrays, cts):
            ct_list = list(cts) if isinstance(cts, (tuple, list)) else [cts]
            grads = jax.pure_callback(
                lambda *a: _np_call(backward_func, in_specs, *a),
                tuple(in_specs), *ct_list)
            return tuple(jnp.asarray(g) for g in grads)

        f.defvjp(_fwd, _bwd)

    return _dispatch.call("py_func", f, xs, multi_output=multi)


class ExponentialMovingAverage:
    """reference static/nn/common.py:4040 ExponentialMovingAverage.

    Tracks shadow (EMA) copies of trainable parameters:
    ``shadow = decay * shadow + (1 - decay) * param`` on every
    ``update()``; ``apply()`` swaps the shadows in for evaluation (as a
    context manager it restores on exit; ``restore()`` does it
    explicitly). ``thres_steps`` enables the reference's ramped decay
    ``min(decay, (1 + t) / (10 + t))``.
    """

    def __init__(self, decay=0.999, thres_steps=None, parameters=None,
                 name=None):
        if parameters is None:
            raise ValueError(
                "ExponentialMovingAverage needs the parameter list "
                "(dygraph-first design: there is no global program to "
                "collect them from)")
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._params = [p for p in parameters if not p.stop_gradient]
        self._shadow = [p._data for p in self._params]
        self._backup = None
        self._step = 0

    def update(self):
        import jax.numpy as jnp
        self._step += 1
        d = self._decay
        if self._thres_steps is not None:
            d = min(d, (1.0 + self._step) / (10.0 + self._step))
        self._shadow = [
            (d * s + (1.0 - d) * p._data).astype(p._data.dtype)
            for s, p in zip(self._shadow, self._params)]

    def apply(self, executor=None, need_restore=True):
        import contextlib

        if self._backup is not None:
            raise RuntimeError(
                "ExponentialMovingAverage.apply() called while shadows "
                "are already applied — a second backup would capture the "
                "shadow values and lose the training weights; call "
                "restore() first")
        self._backup = [p._data for p in self._params]
        for p, s in zip(self._params, self._shadow):
            p._swap_payload(s)

        ema = self

        @contextlib.contextmanager
        def ctx():
            try:
                yield ema
            finally:
                if need_restore:
                    ema.restore()
        return ctx()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._params, self._backup):
            p._swap_payload(b)
        self._backup = None


__all__ = ["fc", "embedding", "sparse_embedding", "conv2d", "batch_norm",
           "layer_norm", "instance_norm", "spectral_norm", "py_func",
           "ExponentialMovingAverage"]
