"""static.nn control-flow surface (reference
python/paddle/static/nn/control_flow.py — cond:1487, while_loop:682,
case, switch_case).

The implementation lives in ``paddle_tpu.ops.control_flow`` so the ops
register with ``ops/registry.py`` at ``import paddle_tpu`` time (the op
sweep and parity audit read the registry); this module is the documented
public surface, matching the reference's file layout. See the
implementation module's docstring for the eager/captured execution
contract.
"""
from __future__ import annotations

from ...ops.control_flow import case, cond, switch_case, while_loop

__all__ = ["cond", "while_loop", "case", "switch_case"]
