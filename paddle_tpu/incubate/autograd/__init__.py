"""paddle.incubate.autograd — functional autograd surface.

Reference: ``python/paddle/incubate/autograd/functional.py`` (vjp :22,
jvp :80, Jacobian :170, Hessian :257). The prim-rule machinery
(primapi/primx) is subsumed by XLA: jax transforms ARE the primitive
rewrite layer, so ``enable_prim`` is a no-op switch kept for import
parity.
"""
from ...autograd import functional as _fn
from ...autograd.functional import jvp, vjp
from ...core.tensor import Tensor as _Tensor


class Jacobian:
    """Lazy Jacobian of ``func`` at ``xs`` (reference
    incubate/autograd/functional.py Jacobian :170 — note the
    *callable-first* signature, unlike paddle.autograd.jacobian which
    takes computed tensors)."""

    def __init__(self, func, xs, is_batched: bool = False):
        xs_t = (xs,) if isinstance(xs, _Tensor) else tuple(xs)
        saved = [x.stop_gradient for x in xs_t]
        for x in xs_t:
            x.stop_gradient = False
        try:
            # build the graph (and the inner object's grad passes) while
            # inputs are unfrozen — ops recorded on frozen tensors don't
            # link back to them; lazy ROW evaluation later is fine on
            # frozen leaves (the graph already exists)
            ys = func(*xs_t)
            batch_axis = 0 if is_batched else None
            self._inner = _fn.jacobian(ys, xs, batch_axis)
        finally:
            for x, s in zip(xs_t, saved):
                x.stop_gradient = s

    @property
    def shape(self):
        inner = self._inner
        return (inner.shape if not isinstance(inner, tuple)
                else tuple(j.shape for j in inner))

    def __getitem__(self, idx):
        inner = self._inner
        if isinstance(inner, tuple):
            # reference: multiple xs concatenate along the input axis
            from ... import ops
            parts = [j[:] for j in inner]
            return ops.concat(parts, axis=-1)[idx]
        return inner[idx]

    def numpy(self):
        import numpy as np
        return np.asarray(self[:].numpy())


class Hessian(Jacobian):
    """Lazy Hessian of scalar-valued ``func`` at ``xs`` (reference
    Hessian :257)."""

    def __init__(self, func, xs, is_batched: bool = False):
        xs_t = (xs,) if isinstance(xs, _Tensor) else tuple(xs)
        saved = [x.stop_gradient for x in xs_t]
        for x in xs_t:
            x.stop_gradient = False
        try:
            ys = func(*xs_t)
            batch_axis = 0 if is_batched else None
            # the create_graph first-grad pass must run while inputs are
            # unfrozen (see Jacobian.__init__)
            self._inner = _fn.hessian(ys, xs, batch_axis)
        finally:
            for x, s in zip(xs_t, saved):
                x.stop_gradient = s

    @property
    def shape(self):
        inner = self._inner
        if not isinstance(inner, tuple):
            return inner.shape
        # flattened block matrix: (sum_N, sum_N) (+ leading batch)
        ns = [row[0].shape[-2] for row in inner]
        total = sum(ns)
        lead = inner[0][0].shape[:-2]
        return tuple(lead) + (total, total)

    def __getitem__(self, idx):
        inner = self._inner
        if isinstance(inner, tuple):
            # reference: multiple xs flatten into one block matrix
            from ... import ops
            rows = [ops.concat([blk[:] for blk in row], axis=-1)
                    for row in inner]
            return ops.concat(rows, axis=-2)[idx]
        return inner[idx]


_prim_enabled = False


def enable_prim():
    """No-op (prim rewriting is XLA's job here); kept for parity."""
    global _prim_enabled
    _prim_enabled = True


def disable_prim():
    global _prim_enabled
    _prim_enabled = False


def prim_enabled():
    return _prim_enabled


__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "enable_prim",
           "disable_prim", "prim_enabled"]
