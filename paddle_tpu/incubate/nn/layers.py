"""incubate.nn fused layers.

Reference: python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention:189, FusedFeedForward:483,
FusedTransformerEncoderLayer:697, FusedBiasDropoutResidualLayerNorm:83),
fused_linear.py, fused_dropout_add.py. The reference fuses these with
hand-written CUDA kernels; on TPU the same graphs are fused by XLA and
the attention core is the Pallas flash kernel — so these layers are the
reference's *module contracts* (same params, same residual/norm
ordering, normalize_before semantics) over the compiler's fusion.
"""
from __future__ import annotations

from ... import nn
from ...nn import functional as F

__all__ = [
    "FusedLinear", "FusedDropoutAdd", "FusedBiasDropoutResidualLayerNorm",
    "FusedMultiHeadAttention", "FusedFeedForward",
    "FusedTransformerEncoderLayer",
]


class FusedLinear(nn.Layer):
    """GEMM + bias epilogue (reference fused_linear.py FusedLinear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        if transpose_weight:
            # weight stored (out, in); bias is ALWAYS (out,)
            self.weight = self.create_parameter(
                (out_features, in_features), attr=weight_attr)
            self.bias = (None if bias_attr is False else
                         self.create_parameter((out_features,),
                                               attr=bias_attr, is_bias=True))
            self.linear = None
        else:
            self.linear = nn.Linear(in_features, out_features,
                                    weight_attr=weight_attr,
                                    bias_attr=bias_attr)
            self.weight = self.linear.weight
            self.bias = self.linear.bias

    def forward(self, x):
        if self.transpose_weight:
            out = x @ self.weight.t()
            if self.bias is not None:
                out = out + self.bias
            return out
        return self.linear(x)


class FusedDropoutAdd(nn.Layer):
    """dropout(x) + y in one fusion (reference fused_dropout_add.py)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.dropout = nn.Dropout(p, mode=mode)

    def forward(self, x, y):
        return self.dropout(x) + y


class FusedBiasDropoutResidualLayerNorm(nn.Layer):
    """LN(residual + dropout(x + bias)) (reference
    fused_transformer.py:83)."""

    def __init__(self, embed_dim, dropout_rate=0.5, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.linear_bias = self.create_parameter((embed_dim,), is_bias=True)
        self.dropout = nn.Dropout(dropout_rate)
        self.norm = nn.LayerNorm(embed_dim, epsilon=epsilon)

    def forward(self, x, residual):
        return self.norm(residual + self.dropout(x + self.linear_bias))


class FusedMultiHeadAttention(nn.Layer):
    """Pre/post-LN multi-head self-attention block with residual
    (reference fused_transformer.py:189). Attention core = flash
    attention; the surrounding LN/residual/dropout fuse under XLA."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        if need_weights:
            raise NotImplementedError(
                "need_weights=True is unsupported (flash attention does "
                "not materialize probabilities)")
        if nranks > 1 or ring_id >= 0:
            raise NotImplementedError(
                "tensor-parallel FusedMultiHeadAttention: use "
                "fleet.ColumnParallelLinear/RowParallelLinear layers (the "
                "mp mesh axis), not nranks/ring_id")
        if (kdim not in (None, embed_dim)) or (vdim not in (None,
                                                            embed_dim)):
            raise NotImplementedError(
                "cross-attention kdim/vdim != embed_dim is unsupported "
                "in the fused layer; use nn.MultiHeadAttention")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        if self.head_dim * num_heads != embed_dim:
            raise ValueError("embed_dim must be divisible by num_heads")
        self.normalize_before = normalize_before
        self.qkv = nn.Linear(embed_dim, 3 * embed_dim,
                             weight_attr=qkv_weight_attr,
                             bias_attr=qkv_bias_attr)
        self.out_proj = nn.Linear(embed_dim, embed_dim,
                                  weight_attr=linear_weight_attr,
                                  bias_attr=linear_bias_attr)
        self.attn_dropout_rate = attn_dropout_rate
        self.dropout = nn.Dropout(dropout_rate)
        norm_w = pre_ln_scale_attr if normalize_before else ln_scale_attr
        norm_b = pre_ln_bias_attr if normalize_before else ln_bias_attr
        self.norm = nn.LayerNorm(embed_dim, epsilon=epsilon,
                                 weight_attr=norm_w, bias_attr=norm_b)

    def forward(self, x, attn_mask=None):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        b, s, _ = x.shape
        from ... import ops
        qkv = ops.reshape(self.qkv(x), [b, s, 3, self.num_heads,
                                        self.head_dim])
        q, k, v = ops.unbind(qkv, axis=2)
        if attn_mask is not None:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask,
                dropout_p=self.attn_dropout_rate if self.training else 0.0,
                training=self.training)
        else:
            out, _ = F.flash_attention(
                q, k, v,
                dropout=self.attn_dropout_rate if self.training else 0.0,
                causal=False, training=self.training)
        out = self.out_proj(ops.reshape(out, [b, s, self.embed_dim]))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(nn.Layer):
    """Pre/post-LN MLP block with residual (reference
    fused_transformer.py:483)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        if nranks > 1 or ring_id >= 0:
            raise NotImplementedError(
                "tensor-parallel FusedFeedForward: use the fleet mp "
                "layers, not nranks/ring_id")
        self.normalize_before = normalize_before
        self.linear1 = nn.Linear(d_model, dim_feedforward,
                                 weight_attr=linear1_weight_attr,
                                 bias_attr=linear1_bias_attr)
        self.linear2 = nn.Linear(dim_feedforward, d_model,
                                 weight_attr=linear2_weight_attr,
                                 bias_attr=linear2_bias_attr)
        self.act = {"relu": F.relu, "gelu": F.gelu}[activation]
        act_dropout_rate = (dropout_rate if act_dropout_rate is None
                            else act_dropout_rate)
        self.act_dropout = nn.Dropout(act_dropout_rate)
        self.dropout = nn.Dropout(dropout_rate)
        norm_w = ln1_scale_attr if normalize_before else ln2_scale_attr
        norm_b = ln1_bias_attr if normalize_before else ln2_bias_attr
        self.norm = nn.LayerNorm(d_model, epsilon=epsilon,
                                 weight_attr=norm_w, bias_attr=norm_b)

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        x = self.linear2(self.act_dropout(self.act(self.linear1(x))))
        out = residual + self.dropout(x)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(nn.Layer):
    """FusedMultiHeadAttention + FusedFeedForward (reference
    fused_transformer.py:697)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                             else attn_dropout_rate)
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before,
            qkv_weight_attr=weight_attr, qkv_bias_attr=bias_attr,
            linear_weight_attr=weight_attr, linear_bias_attr=bias_attr)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before,
            linear1_weight_attr=weight_attr, linear1_bias_attr=bias_attr,
            linear2_weight_attr=weight_attr, linear2_bias_attr=bias_attr)

    def forward(self, src, src_mask=None, cache=None):
        if cache is not None:
            raise NotImplementedError(
                "incremental-decode cache is not supported here; use "
                "nn.functional.block_multihead_attention for cached "
                "serving")
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))
