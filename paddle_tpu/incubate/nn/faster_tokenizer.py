"""FasterTokenizer: in-framework BERT tokenization over StringTensor.

Reference contract: ``paddle/fluid/operators/string/faster_tokenizer_op.h``
(BasicTokenizer / WordPieceTokenizer / BertTokenizer and the
``faster_tokenizer`` op: Text [+ TextPair] + Vocab → InputIds, SegmentIds)
and ``faster_tokenizer_op.cc`` for the exact character-class and wordpiece
semantics.

TPU-first design: tokenization is host work — the reference also runs it on
CPU inside the op. Here the tokenizer consumes a host ``StringTensor`` (or
plain python strings) and emits device int32 id tensors, the natural handoff
point to XLA (int32 over int64: TPU-native index dtype; ids are vocab-sized
so int32 is lossless).

Character classes mirror ``faster_tokenizer_op.cc``:
* control: U+0000/U+FFFD dropped; ``Cc``/``Cf`` dropped except tab/LF/CR
  (``IsControl``, :43)
* whitespace: space/tab/LF/CR or category ``Zs`` (``IsWhiteSpace``, :59)
* punctuation: ASCII punct blocks or any ``P*`` category
  (``IsPunctuation``, :70)
* CJK: the ideograph ranges of ``IsChineseChar`` (:50), always split as
  single-char tokens and looked up whole (BertTokenizer::Tokenize :219)
* lowercase: 1:1 per-codepoint ``utf8proc_tolower`` (:82)
"""
from __future__ import annotations

import unicodedata
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ...core.string_tensor import StringTensor

__all__ = ["BasicTokenizer", "WordPieceTokenizer", "BertTokenizer",
           "FasterTokenizer", "load_vocab"]

Vocab = Dict[str, int]


def load_vocab(path: str) -> Vocab:
    """Load a BERT ``vocab.txt`` (one token per line, id = line number)."""
    vocab: Vocab = {}
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            tok = line.rstrip("\n")
            if tok:
                vocab[tok] = i
    return vocab


def _is_control(ch: str) -> bool:
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch) in ("Cc", "Cf")


def _is_whitespace(ch: str) -> bool:
    if ch in (" ", "\t", "\n", "\r"):
        return True
    return unicodedata.category(ch) == "Zs"


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_chinese_char(ch: str) -> bool:
    cp = ord(ch)
    return ((0x4E00 <= cp <= 0x9FFF) or (0x3400 <= cp <= 0x4DBF)
            or (0x20000 <= cp <= 0x2A6DF) or (0x2A700 <= cp <= 0x2B73F)
            or (0x2B740 <= cp <= 0x2B81F) or (0x2B820 <= cp <= 0x2CEAF)
            or (0xF900 <= cp <= 0xFAFF) or (0x2F800 <= cp <= 0x2FA1F))


def _char_lower(ch: str) -> str:
    # utf8proc_tolower is a 1:1 codepoint map; keep multi-char expansions out
    low = ch.lower()
    return low if len(low) == 1 else ch


class BasicTokenizer:
    """Whitespace/punct/CJK splitter (reference BasicTokenizer::Tokenize)."""

    def __init__(self, do_lower_case: bool = True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text: str) -> List[str]:
        tokens: List[str] = []
        cache: List[str] = []

        def flush():
            if cache:
                tokens.append("".join(cache))
                cache.clear()

        for ch in text:
            if ch == "\x00" or ch == "�" or _is_control(ch):
                continue
            if self.do_lower_case:
                ch = _char_lower(ch)
            if _is_chinese_char(ch) or _is_punctuation(ch):
                flush()
                tokens.append(ch)
            elif _is_whitespace(ch):
                flush()
            else:
                cache.append(ch)
        flush()
        return tokens


class WordPieceTokenizer:
    """Greedy longest-match-first subword splitter, ``##`` continuations."""

    def __init__(self, vocab: Vocab, unk_token: str = "[UNK]",
                 max_input_chars_per_word: int = 100):
        self.vocab = vocab
        self.unk_token_id = vocab[unk_token]
        self.max_input_chars_per_word = max_input_chars_per_word

    def tokenize(self, word: str) -> List[int]:
        n = len(word)
        if n > self.max_input_chars_per_word:
            return [self.unk_token_id]
        whole = self.vocab.get(word)
        if whole is not None:
            return [whole]
        ids: List[int] = []
        start = 0
        while start < n:
            end = n
            hit = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                hit = self.vocab.get(sub)
                if hit is not None:
                    break
                end -= 1
            if hit is None:
                return [self.unk_token_id]  # whole word → UNK, not partial
            ids.append(hit)
            start = end
        return ids


class BertTokenizer:
    """Full encode pipeline (reference BertTokenizer)."""

    def __init__(self, vocab: Vocab, do_lower_case: bool = False,
                 unk_token: str = "[UNK]", pad_token: str = "[PAD]",
                 cls_token: str = "[CLS]", mask_token: str = "[MASK]",
                 sep_token: str = "[SEP]"):
        self.vocab = vocab
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordPieceTokenizer(vocab, unk_token)
        self.unk_token_id = vocab[unk_token]
        self.pad_token_id = vocab[pad_token]
        self.cls_token_id = vocab[cls_token]
        self.sep_token_id = vocab[sep_token]
        self.mask_token_id = vocab.get(mask_token)

    def tokenize(self, text: str) -> List[int]:
        ids: List[int] = []
        for tok in self.basic.tokenize(text):
            if len(tok) == 1 and _is_chinese_char(tok):
                ids.append(self.vocab.get(tok, self.unk_token_id))
            else:
                ids.extend(self.wordpiece.tokenize(tok))
        return ids

    def num_special_tokens_to_add(self, pair: bool = False) -> int:
        return 3 if pair else 2  # [CLS] a [SEP] (b [SEP])

    def build_inputs_with_special_tokens(
            self, ids: List[int],
            pair_ids: Optional[List[int]] = None) -> List[int]:
        out = [self.cls_token_id] + ids + [self.sep_token_id]
        if pair_ids:
            out += pair_ids + [self.sep_token_id]
        return out

    def create_token_type_ids(self, ids: List[int],
                              pair_ids: Optional[List[int]] = None
                              ) -> List[int]:
        tt = [0] * (len(ids) + 2)
        if pair_ids:
            tt += [1] * (len(pair_ids) + 1)
        return tt

    def truncate_sequence(self, ids: List[int], pair_ids: List[int],
                          num_tokens_to_remove: int = 0) -> None:
        # longest-first, one token at a time (reference TruncateSequence)
        for _ in range(num_tokens_to_remove):
            if not ids and not pair_ids:
                return  # nothing left; encode's length check rejects below
            if not pair_ids or len(ids) > len(pair_ids):
                ids.pop()
            else:
                pair_ids.pop()

    def encode(self, text: str, text_pair: str = "",
               is_split_into_words: bool = False, max_seq_len: int = 0,
               pad_to_max_seq_len: bool = False
               ) -> Optional[Dict[str, List[int]]]:
        if not is_split_into_words:
            ids = self.tokenize(text)
            if not ids:
                return None
            pair_ids = self.tokenize(text_pair) if text_pair else []
            if text_pair and not pair_ids:
                return None
        else:
            # char-per-token mode: each codepoint looked up directly
            ids = [self.vocab.get(c, self.unk_token_id) for c in text]
            pair_ids = []

        total = (len(ids) + len(pair_ids)
                 + self.num_special_tokens_to_add(bool(pair_ids)))
        if max_seq_len and total > max_seq_len:
            self.truncate_sequence(ids, pair_ids, total - max_seq_len)

        input_ids = self.build_inputs_with_special_tokens(ids, pair_ids)
        token_type_ids = self.create_token_type_ids(ids, pair_ids)
        if max_seq_len and len(input_ids) > max_seq_len:
            return None
        if pad_to_max_seq_len and max_seq_len and len(input_ids) < max_seq_len:
            # right-pad both streams with pad_token_id (reference Encode)
            pad = max_seq_len - len(input_ids)
            input_ids += [self.pad_token_id] * pad
            token_type_ids += [self.pad_token_id] * pad
        return {"input_ids": input_ids, "token_type_ids": token_type_ids}

    def batch_encode(self, texts: Sequence[str],
                     text_pairs: Optional[Sequence[str]] = None,
                     is_split_into_words: bool = False,
                     max_seq_len: int = 0,
                     pad_to_max_seq_len: bool = False
                     ) -> List[Dict[str, List[int]]]:
        if text_pairs is not None and len(text_pairs) != len(texts):
            raise ValueError(
                f"text ({len(texts)}) and text_pair ({len(text_pairs)}) "
                "must have the same number of sequences")
        out = []
        for i, t in enumerate(texts):
            enc = self.encode(
                t, text_pairs[i] if text_pairs is not None else "",
                is_split_into_words, max_seq_len, pad_to_max_seq_len)
            out.append(enc or {"input_ids": [], "token_type_ids": []})
        return out


class FasterTokenizer:
    """The ``faster_tokenizer`` op as a host layer: strings in, ids out.

    forward(text[, text_pair]) → (input_ids, token_type_ids) as device
    int32 tensors, batch right-padded to the batch max length with the pad
    token id (reference FasterTokenizerKernel::Compute).
    """

    def __init__(self, vocab: Union[Vocab, str], do_lower_case: bool = False,
                 is_split_into_words: bool = False, max_seq_len: int = 0,
                 pad_to_max_seq_len: bool = False):
        if isinstance(vocab, str):
            vocab = load_vocab(vocab)
        self.tokenizer = BertTokenizer(vocab, do_lower_case)
        self.is_split_into_words = is_split_into_words
        self.max_seq_len = max_seq_len
        self.pad_to_max_seq_len = pad_to_max_seq_len

    @staticmethod
    def _as_texts(x) -> List[str]:
        if x is None:
            return None
        if isinstance(x, StringTensor):
            return [str(s) for s in x.reshape([-1]).tolist()]
        if isinstance(x, str):
            return [x]
        return [str(s) for s in x]

    def forward(self, text, text_pair=None):
        from ... import to_tensor

        texts = self._as_texts(text)
        pairs = self._as_texts(text_pair)
        encoded = self.tokenizer.batch_encode(
            texts, pairs, self.is_split_into_words, self.max_seq_len,
            self.pad_to_max_seq_len)
        pad_id = self.tokenizer.pad_token_id
        batch_max = max((len(e["input_ids"]) for e in encoded), default=0)
        n = len(encoded)
        input_ids = np.full((n, batch_max), pad_id, dtype=np.int32)
        token_type_ids = np.full((n, batch_max), pad_id, dtype=np.int32)
        for i, e in enumerate(encoded):
            L = len(e["input_ids"])
            input_ids[i, :L] = e["input_ids"]
            token_type_ids[i, :L] = e["token_type_ids"]
        return to_tensor(input_ids), to_tensor(token_type_ids)

    __call__ = forward
