"""incubate.nn.functional — reference import path for serving-fused
attention (reference: python/paddle/incubate/nn/functional/)."""
from ....nn.functional.paged_attention import block_multihead_attention

__all__ = ["block_multihead_attention"]
