"""incubate.nn: MoE layers at the reference import path (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py MoELayer)."""
from ...distributed.fleet.moe import MoELayer, TopKGate
from . import functional  # noqa: F401

__all__ = ["MoELayer", "TopKGate", "functional"]
