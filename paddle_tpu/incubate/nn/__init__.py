"""incubate.nn: MoE layers at the reference import path (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py MoELayer)."""
from ...distributed.fleet.moe import MoELayer, TopKGate

__all__ = ["MoELayer", "TopKGate"]
