"""incubate.nn: MoE layers at the reference import path (reference:
python/paddle/incubate/distributed/models/moe/moe_layer.py MoELayer)."""
from ...distributed.fleet.moe import MoELayer, TopKGate
from . import functional  # noqa: F401
from .layers import (FusedBiasDropoutResidualLayerNorm, FusedDropoutAdd,
                     FusedFeedForward, FusedLinear,
                     FusedMultiHeadAttention,
                     FusedTransformerEncoderLayer)
from .faster_tokenizer import (BertTokenizer, FasterTokenizer, load_vocab)

__all__ = ["MoELayer", "TopKGate", "functional", "FusedLinear",
           "FusedDropoutAdd", "FusedBiasDropoutResidualLayerNorm",
           "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FasterTokenizer",
           "BertTokenizer", "load_vocab"]
