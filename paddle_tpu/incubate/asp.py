"""Automatic SParsity (ASP): n:m structured sparsity training.

Reference: python/paddle/incubate/asp/ — utils.py (get_mask_1d:~,
get_mask_2d_greedy, check_mask_*, calculate_density, MaskAlgo/CheckMethod),
asp.py (prune_model:302, decorate:216 re-applying masks after each
optimizer step, set/reset_excluded_layers). The reference targets
Ampere's 2:4 sparse tensor cores; on TPU the win is model compression +
the masked weights staying exactly zero through training (the MXU has no
sparse mode, so masked matmuls run dense — the capability preserved here
is the TRAINING protocol and the checkable n:m structure).
"""
from __future__ import annotations

import enum
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = [
    "MaskAlgo", "CheckMethod", "calculate_density", "get_mask_1d",
    "get_mask_2d_greedy", "check_mask_1d", "check_mask_2d",
    "check_sparsity", "create_mask", "prune_model", "decorate",
    "set_excluded_layers", "reset_excluded_layers", "ASPHelper",
]


class MaskAlgo(enum.Enum):
    MASK_1D = "mask_1d"
    MASK_2D_GREEDY = "mask_2d_greedy"
    MASK_2D_BEST = "mask_2d_best"


class CheckMethod(enum.Enum):
    CHECK_1D = "check_1d"
    CHECK_2D = "check_2d"

    @staticmethod
    def get_checking_method(mask_algo: MaskAlgo):
        if mask_algo == MaskAlgo.MASK_1D:
            return CheckMethod.CHECK_1D
        return CheckMethod.CHECK_2D


def calculate_density(x) -> float:
    """Fraction of nonzeros (reference utils.calculate_density)."""
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def get_mask_1d(mat: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """Keep the n largest-|w| of every m consecutive elements per row
    (reference utils.get_mask_1d)."""
    mat = np.asarray(mat)
    shape = mat.shape
    flat = mat.reshape(-1)
    pad = (-flat.size) % m
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    groups = np.abs(flat.reshape(-1, m))
    # indices of the (m-n) smallest per group -> zeroed
    order = np.argsort(groups, axis=1)
    mask = np.ones_like(groups, dtype=bool)
    np.put_along_axis(mask, order[:, :m - n], False, axis=1)
    mask = mask.reshape(-1)[:mat.size].reshape(shape)
    return mask.astype(mat.dtype if np.issubdtype(mat.dtype, np.floating)
                       else np.float32)


def get_mask_2d_greedy(mat: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """Greedy 2D n:m mask over m x m tiles: keep the largest entries while
    keeping every row AND column of the tile at most n nonzeros
    (reference utils.get_mask_2d_greedy)."""
    mat = np.asarray(mat)
    if mat.ndim != 2:
        return get_mask_1d(mat, n, m)
    rows, cols = mat.shape
    pr, pc = (-rows) % m, (-cols) % m
    work = np.abs(np.pad(mat, ((0, pr), (0, pc))))
    mask = np.zeros_like(work, dtype=bool)
    for r0 in range(0, work.shape[0], m):
        for c0 in range(0, work.shape[1], m):
            tile = work[r0:r0 + m, c0:c0 + m]
            order = np.argsort(-tile, axis=None)
            rcnt = np.zeros(m, np.int64)
            ccnt = np.zeros(m, np.int64)
            for flat_i in order:
                i, j = divmod(int(flat_i), m)
                if rcnt[i] < n and ccnt[j] < n:
                    mask[r0 + i, c0 + j] = True
                    rcnt[i] += 1
                    ccnt[j] += 1
    mask = mask[:rows, :cols]
    return mask.astype(mat.dtype if np.issubdtype(mat.dtype, np.floating)
                       else np.float32)


get_mask_2d_best = get_mask_2d_greedy  # greedy is the practical reference


def check_mask_1d(mat: np.ndarray, n: int = 2, m: int = 4) -> bool:
    """Every m consecutive elements hold at most n nonzeros (reference
    utils.check_mask_1d)."""
    flat = np.asarray(mat).reshape(-1)
    pad = (-flat.size) % m
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    return bool(((flat.reshape(-1, m) != 0).sum(axis=1) <= n).all())


def check_mask_2d(mat: np.ndarray, n: int = 2, m: int = 4) -> bool:
    """Every m x m tile has per-row and per-column nonzeros <= n."""
    mat = np.asarray(mat)
    if mat.ndim != 2:
        return check_mask_1d(mat, n, m)
    rows, cols = mat.shape
    pr, pc = (-rows) % m, (-cols) % m
    work = np.pad(mat, ((0, pr), (0, pc))) != 0
    for r0 in range(0, work.shape[0], m):
        for c0 in range(0, work.shape[1], m):
            tile = work[r0:r0 + m, c0:c0 + m]
            if (tile.sum(axis=0) > n).any() or (tile.sum(axis=1) > n).any():
                return False
    return True


def check_sparsity(mat, n: int = 2, m: int = 4,
                   func_name: CheckMethod = CheckMethod.CHECK_1D) -> bool:
    arr = np.asarray(mat.numpy() if isinstance(mat, Tensor) else mat)
    if func_name == CheckMethod.CHECK_1D:
        return check_mask_1d(arr, n, m)
    return check_mask_2d(arr, n, m)


def create_mask(tensor, func_name: MaskAlgo = MaskAlgo.MASK_1D, n: int = 2,
                m: int = 4):
    arr = np.asarray(tensor.numpy() if isinstance(tensor, Tensor)
                     else tensor)
    if func_name == MaskAlgo.MASK_1D:
        return get_mask_1d(arr, n, m)
    return get_mask_2d_greedy(arr, n, m)


class ASPHelper:
    """Mask registry + training protocol (reference asp.py ASPHelper).

    Masks are stored per parameter name; ``prune_model`` computes and
    applies them, and a decorated optimizer re-applies them after every
    ``step()`` so pruned weights stay exactly zero through training."""

    # id(param) -> (weakref to param, mask): the weakref guards against
    # CPython id reuse after a pruned model is garbage collected, and
    # keys masks per PARAMETER so two models sharing layer names cannot
    # alias each other's masks
    _masks_by_id: Dict[int, tuple] = {}
    _masks: Dict[str, np.ndarray] = {}      # name -> mask (introspection)
    _excluded: set = set()

    MIN_PRUNABLE_DIM = 2

    @classmethod
    def is_supported_layer(cls, param_name: str, param) -> bool:
        # exact name, or a dotted-prefix (layer path) match — plain
        # substring would over-match ("0." is inside "10.weight")
        def excluded(e):
            prefix = e if e.endswith(".") else e + "."
            return param_name == e or param_name.startswith(prefix)

        if any(excluded(e) for e in cls._excluded):
            return False
        shape = param.shape
        # prune matmul-class weights only (reference supported_layer_list:
        # Linear/Conv kernels, not biases/norms/embeddings)
        return len(shape) >= cls.MIN_PRUNABLE_DIM and min(
            int(s) for s in shape) >= 4 and "bias" not in param_name

    @classmethod
    def prune_model(cls, model, n=2, m=4, mask_algo=MaskAlgo.MASK_1D,
                    with_mask=True):
        masks = {}
        for name, p in model.named_parameters():
            if p.stop_gradient or not cls.is_supported_layer(name, p):
                continue
            w = np.asarray(p.numpy())
            flat2d = w.reshape(w.shape[0], -1)
            mask = create_mask(flat2d, mask_algo, n, m).reshape(w.shape)
            p.set_value(Tensor(jnp.asarray(w * mask)))
            if with_mask:
                import weakref
                masks[name] = mask
                cls._masks_by_id[id(p)] = (weakref.ref(p), mask)
        cls._masks.update(masks)
        return masks

    @classmethod
    def mask_for(cls, p):
        entry = cls._masks_by_id.get(id(p))
        if entry is None:
            return None
        ref, mask = entry
        if ref() is not p:   # stale id reuse: drop the dead entry
            del cls._masks_by_id[id(p)]
            return None
        return mask

    @classmethod
    def apply_masks(cls, model):
        for _, p in model.named_parameters():
            mask = cls.mask_for(p)
            if mask is not None:
                p.set_value(Tensor(p._data * jnp.asarray(mask)))

    @classmethod
    def reset(cls):
        cls._masks.clear()
        cls._masks_by_id.clear()


def set_excluded_layers(param_names: List[str], main_program=None):
    """reference asp.py:40 — exclude parameters (by name/prefix) from
    pruning."""
    ASPHelper._excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    ASPHelper._excluded.clear()


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Prune supported weights to n:m sparsity (reference asp.py:302)."""
    algo = MaskAlgo(mask_algo) if not isinstance(mask_algo, MaskAlgo) \
        else mask_algo
    return ASPHelper.prune_model(model, n=n, m=m, mask_algo=algo,
                                 with_mask=with_mask)


class _DecoratedStep:
    def __init__(self, optimizer):
        self._opt = optimizer
        self._orig_step = optimizer.step

    def __call__(self, *args, **kwargs):
        out = self._orig_step(*args, **kwargs)
        # re-apply masks to every registered param this optimizer owns;
        # if the optimizer stores params elsewhere (param groups, custom
        # subclass), fall back to ALL live registered masks so pruned
        # weights can never silently drift nonzero
        params = getattr(self._opt, "_parameter_list", None)
        if not params:
            params = [ref() for ref, _m in ASPHelper._masks_by_id.values()]
            params = [p for p in params if p is not None]
        for p in params:
            mask = ASPHelper.mask_for(p)
            if mask is not None:
                p.set_value(Tensor(p._data * jnp.asarray(mask)))
        return out


def decorate(optimizer):
    """Wrap optimizer.step to re-apply sparsity masks after each update
    (reference asp.py:216 OptimizerWithSparsityGuarantee)."""
    optimizer.step = _DecoratedStep(optimizer)
    return optimizer
