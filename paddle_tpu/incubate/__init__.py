"""paddle.incubate surface: experimental APIs kept at reference import
paths (reference: python/paddle/incubate/)."""
from . import asp, autograd, nn  # noqa: F401

__all__ = ["nn", "asp", "autograd"]
