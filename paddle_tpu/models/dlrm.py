"""DLRM-style recsys model — the giant-embedding ladder workload.

The reference serves this shape of model (dense MLP + many multi-hot
sparse fields + dot interaction) from its host parameter-server tier;
here the sparse fields share ONE mesh-sharded table
(:class:`~paddle_tpu.distributed.embedding.ShardedEmbedding`, vocab
row-sharded over ``(fsdp, tp)``) so the capacity lives on chip. The
model is the ``embedding`` bench rung's workload and doubles as the
dense-path serving fixture: :meth:`DLRM.serve_dense` scores a flat id
batch in one forward, which ``PagedEngine`` runs behind the Router
without any KV cache.

Architecture (Naumov et al., arXiv:1906.00091):

* bottom MLP over the dense features -> a ``D``-dim dense vector,
* per-field ``sum``-pooled embedding bags over the shared table
  (``ids`` is ``(B, F, L)`` multi-hot, pooled to ``(B, F, D)``),
* dot interaction: the full flattened Gram matrix of the ``F + 1``
  ``D``-dim vectors (fixed shape — no triangular gather needed),
* top MLP over ``[dense_vec, interactions]`` -> one CTR logit.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

from .. import nn
from .. import ops
from ..distributed.embedding import ShardedEmbedding
from ..nn import functional as F


@dataclass
class DLRMConfig:
    num_embeddings: int = 100_000     #: shared-table vocab (all fields)
    embedding_dim: int = 16
    n_dense: int = 4                  #: dense (continuous) features
    n_sparse: int = 8                 #: sparse fields F
    bag_size: int = 4                 #: multi-hot ids per field L
    bottom_mlp: Tuple[int, ...] = (32,)   #: hidden widths (out is D)
    top_mlp: Tuple[int, ...] = (64,)      #: hidden widths (out is 1)
    #: mesh axes the table's vocab dim shards over (axes missing from
    #: the mesh, or of size 1, are skipped)
    embedding_axes: Tuple[str, ...] = ("fsdp", "tp")
    dedup: bool = True                #: dedup ids before the exchange
    dedup_capacity: Optional[int] = None

    def __post_init__(self):
        if self.n_sparse < 1 or self.bag_size < 1:
            raise ValueError("n_sparse and bag_size must be >= 1")


def _mlp(widths: Sequence[int], sigmoid_last: bool = False) -> nn.Layer:
    layers = []
    for i in range(len(widths) - 1):
        layers.append(nn.Linear(widths[i], widths[i + 1]))
        last = i == len(widths) - 2
        layers.append(nn.Sigmoid() if (last and sigmoid_last)
                      else nn.ReLU())
    if not sigmoid_last:
        layers = layers[:-1]          # raw output on the last layer
    return nn.Sequential(*layers)


class DLRM(nn.Layer):
    """DLRM over one shared :class:`ShardedEmbedding` table.

    Pass ``mesh`` (or call :meth:`shard_` later) to row-shard the table
    over ``cfg.embedding_axes``; without a mesh the table is replicated
    — that is the loss-parity baseline the bench rung compares against.
    """

    def __init__(self, cfg: DLRMConfig, mesh=None):
        super().__init__()
        self.cfg = cfg
        d = cfg.embedding_dim
        self.embedding = ShardedEmbedding(
            cfg.num_embeddings, d, mesh=mesh,
            axes=cfg.embedding_axes, dedup=cfg.dedup,
            dedup_capacity=cfg.dedup_capacity)
        self.bottom = _mlp((cfg.n_dense,) + tuple(cfg.bottom_mlp) + (d,))
        n_vec = cfg.n_sparse + 1
        top_in = d + n_vec * n_vec    # dense vec + flat Gram matrix
        self.top = _mlp((top_in,) + tuple(cfg.top_mlp) + (1,))
        #: flat-id width PagedEngine's dense path pads prompts to
        self.serve_dense_width = cfg.n_sparse * cfg.bag_size

    def shard_(self, mesh=None) -> "DLRM":
        self.embedding.shard_(mesh)
        return self

    def forward(self, dense, ids):
        """``dense``: (B, n_dense) float; ``ids``: (B, F, L) int.
        Returns the (B,) CTR logit."""
        cfg = self.cfg
        x = self.bottom(dense)                        # (B, D)
        pooled = self.embedding.bag(ids)              # (B, F, D)
        z = ops.concat(
            [ops.reshape(x, [-1, 1, cfg.embedding_dim]), pooled],
            axis=1)                                   # (B, F+1, D)
        gram = ops.matmul(z, ops.transpose(z, [0, 2, 1]))
        n_vec = cfg.n_sparse + 1
        feats = ops.concat(
            [x, ops.reshape(gram, [-1, n_vec * n_vec])], axis=1)
        logit = self.top(feats)                       # (B, 1)
        return ops.reshape(logit, [-1])

    def loss(self, dense, ids, labels):
        """Mean BCE-with-logits over the batch (the rung's parity
        metric)."""
        return F.binary_cross_entropy_with_logits(
            self.forward(dense, ids), labels)

    def serve_dense(self, flat_ids):
        """One-forward scoring for the serving dense path:
        ``flat_ids`` is (B, F*L) int (each row a request's ids padded
        to :attr:`serve_dense_width`), dense features are zero, and the
        result is the (B,) sigmoid click score."""
        cfg = self.cfg
        ids = ops.reshape(flat_ids, [-1, cfg.n_sparse, cfg.bag_size])
        b = ids.shape[0]
        dense = ops.zeros([b, cfg.n_dense], dtype="float32")
        return F.sigmoid(self.forward(dense, ids))


def dlrm_tiny(**kw) -> DLRMConfig:
    """Smoke-scale config (tests, the serving fixture)."""
    kw.setdefault("num_embeddings", 512)
    kw.setdefault("embedding_dim", 8)
    kw.setdefault("n_sparse", 4)
    kw.setdefault("bag_size", 2)
    kw.setdefault("bottom_mlp", (16,))
    kw.setdefault("top_mlp", (16,))
    return DLRMConfig(**kw)
