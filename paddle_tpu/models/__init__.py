"""Model zoo (reference: python/paddle/vision/models + the GPT fixtures the
reference uses for auto-parallel tests, test/auto_parallel/get_gpt_model.py).
These are the BASELINE.md ladder configs: LeNet, ResNet, BERT, GPT, LLaMA.
"""
from .lenet import LeNet
from .gpt import GPTConfig, GPTModel, GPTForCausalLM, gpt2_small, gpt2_medium
from .bert import (BertConfig, BertForPretraining,
                   BertForSequenceClassification, BertModel)
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel,
                    llama_7b, llama_tiny, llama2_13b, llama2_70b)
from .dlrm import DLRM, DLRMConfig, dlrm_tiny

__all__ = [
    "LeNet", "GPTConfig", "GPTModel", "GPTForCausalLM",
    "DLRM", "DLRMConfig", "dlrm_tiny",
    "BertConfig", "BertModel", "BertForPretraining",
    "BertForSequenceClassification",
    "LlamaConfig", "LlamaModel", "LlamaForCausalLM",
    "llama_7b", "llama_tiny", "llama2_13b", "llama2_70b",
    "gpt2_small", "gpt2_medium",
]
