"""Model zoo (reference: python/paddle/vision/models + the GPT fixtures the
reference uses for auto-parallel tests, test/auto_parallel/get_gpt_model.py).
These are the BASELINE.md ladder configs: LeNet, ResNet, BERT, GPT, LLaMA.
"""
from .lenet import LeNet
from .gpt import GPTConfig, GPTModel, GPTForCausalLM, gpt2_small, gpt2_medium

__all__ = [
    "LeNet", "GPTConfig", "GPTModel", "GPTForCausalLM",
    "gpt2_small", "gpt2_medium",
]
