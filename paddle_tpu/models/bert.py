"""BERT encoder family.

Capability parity with the reference BERT fixture used for ladder config 3
(reference: test/legacy_test/test_bert fixtures; PaddleNLP BertModel has the
same structure: embeddings (word+position+token_type) -> LayerNorm ->
TransformerEncoder -> pooler). TPU-native: built on the framework's
TransformerEncoder (XLA-fused attention), bf16-friendly, trainable under
``paddle.jit.to_static`` for the BASELINE.md BERT-base rung.
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn, ops
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.parameter import ParamAttr


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    recompute: bool = False          # activation-checkpoint every layer
    #: fused MLM decoder + chunked streaming CE over the tied embedding
    #: matrix (forward returns (None, nsp_logits, loss) with labels)
    fused_loss: bool = False


def bert_base(**kw) -> "BertConfig":
    return BertConfig(**kw)


def bert_large(**kw) -> "BertConfig":
    kw.setdefault("hidden_size", 1024)
    kw.setdefault("num_hidden_layers", 24)
    kw.setdefault("num_attention_heads", 16)
    kw.setdefault("intermediate_size", 4096)
    return BertConfig(**kw)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        attr = ParamAttr(initializer=Normal(0.0, cfg.initializer_range))
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                            weight_attr=attr)
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size, weight_attr=attr)
        self.token_type_embeddings = nn.Embedding(
            cfg.type_vocab_size, cfg.hidden_size, weight_attr=attr)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        b, s = input_ids.shape
        pos = ops.arange(0, s, dtype="int64")
        x = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            x = x + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertPooler(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, hidden):
        return ops.tanh(self.dense(hidden[:, 0]))


class BertModel(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads,
            cfg.intermediate_size, dropout=cfg.hidden_dropout_prob,
            activation="gelu",
            attn_dropout=cfg.attention_probs_dropout_prob)
        self.encoder = nn.TransformerEncoder(enc_layer,
                                             cfg.num_hidden_layers)
        self.pooler = BertPooler(cfg)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        if attention_mask is not None:
            # [B, S] 1/0 -> additive [B, 1, 1, S]
            m = ops.reshape(attention_mask,
                            [attention_mask.shape[0], 1, 1, -1])
            attention_mask = (1.0 - m.astype("float32")) * -1e4
        if self.cfg.recompute:
            from ._remat import remat_block
            seq = x
            for mod in self.encoder.layers:
                if attention_mask is None:
                    seq = remat_block(mod, seq)
                else:
                    seq = remat_block(mod, seq, attention_mask)
        else:
            seq = self.encoder(x, src_mask=attention_mask)
        return seq, self.pooler(seq)


class BertForSequenceClassification(nn.Layer):
    def __init__(self, cfg: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        return logits, F.cross_entropy(logits, labels)


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (reference BertForPretraining)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.mlm_dense = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size)
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_labels=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_dense(seq), approximate=True))
        # tied decoder: project onto word embedding matrix
        w = self.bert.embeddings.word_embeddings.weight
        if masked_lm_labels is not None and self.bert.cfg.fused_loss:
            hidden = self.bert.cfg.hidden_size
            loss = F.fused_linear_cross_entropy(
                ops.reshape(h, [-1, hidden]), w,
                ops.reshape(masked_lm_labels, [-1]), transpose_y=True,
                ignore_index=-100)
            nsp_logits = self.nsp(pooled)
            if next_sentence_labels is not None:
                loss = loss + F.cross_entropy(nsp_logits,
                                              next_sentence_labels)
            return None, nsp_logits, loss
        mlm_logits = ops.matmul(h, w, transpose_y=True)
        nsp_logits = self.nsp(pooled)
        if masked_lm_labels is None:
            return mlm_logits, nsp_logits
        v = mlm_logits.shape[-1]
        mlm_loss = F.cross_entropy(
            ops.reshape(mlm_logits, [-1, v]),
            ops.reshape(masked_lm_labels, [-1]), ignore_index=-100)
        loss = mlm_loss
        if next_sentence_labels is not None:
            loss = loss + F.cross_entropy(nsp_logits,
                                          next_sentence_labels)
        return mlm_logits, nsp_logits, loss
