"""LLaMA-family decoder model — the hybrid-parallel north star.

Capability parity with the reference's LLaMA support (reference: the
fleet hybrid-parallel stack is exercised by PaddleNLP's LLaMA configs —
test/auto_parallel fixtures; RoPE/RMSNorm/SwiGLU ops in
paddle/phi/ops/yaml: rms_norm, swiglu, fused_rope). TPU-native: RoPE is a
fused jnp expression, attention is the Pallas flash kernel (or ring
attention over the sep axis for long context), GQA repeats KV heads inside
the kernel-feeding reshape, and mp_degree>1 builds the Megatron TP layers
so weights carry 'mp' shardings.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn, ops
from ..core import dispatch
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.parameter import ParamAttr


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 0            # 0 -> = num_heads (MHA); < heads = GQA
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-6
    use_flash_attention: bool = True
    tie_embeddings: bool = False
    mp_degree: int = 1
    sequence_parallel: bool = False
    context_parallel: str = ""       # "", "ring", "ulysses"

    def __post_init__(self):
        if self.num_kv_heads == 0:
            self.num_kv_heads = self.num_heads
        if self.num_heads % self.num_kv_heads:
            raise ValueError("num_heads must be divisible by num_kv_heads")
        if self.context_parallel not in ("", "ring", "ulysses"):
            raise ValueError(f"bad context_parallel "
                             f"{self.context_parallel!r}")


def llama_7b(**kw) -> LlamaConfig:
    return LlamaConfig(**kw)


def llama_tiny(**kw) -> LlamaConfig:
    kw.setdefault("vocab_size", 512)
    kw.setdefault("hidden_size", 128)
    kw.setdefault("intermediate_size", 256)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_seq_len", 128)
    return LlamaConfig(**kw)


def rotary_embedding(x, theta: float = 10000.0, pos_offset: int = 0):
    """Apply RoPE to [B, S, H, D] (reference fused_rope op). Pairs are the
    (even, odd) channel convention."""
    def f(a):
        b, s, h, d = a.shape
        half = d // 2
        freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32)
                                 / half))
        pos = jnp.arange(pos_offset, pos_offset + s,
                         dtype=jnp.float32)[:, None] * freqs[None, :]
        cos = jnp.cos(pos)[None, :, None, :]
        sin = jnp.sin(pos)[None, :, None, :]
        x1, x2 = a[..., :half], a[..., half:]
        return jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin],
            axis=-1).astype(a.dtype)
    return dispatch.call("rotary_embedding", f,
                         [x if isinstance(x, Tensor) else Tensor(x)])


def _linears(cfg: LlamaConfig):
    if cfg.mp_degree > 1:
        from ..distributed import fleet
        if cfg.sequence_parallel:
            return (fleet.ColumnSequenceParallelLinear,
                    fleet.RowSequenceParallelLinear,
                    fleet.VocabParallelEmbedding)
        return (fleet.ColumnParallelLinear, fleet.RowParallelLinear,
                fleet.VocabParallelEmbedding)
    return None, None, None


def _make_linear(cls, in_f, out_f, is_row=False):
    if cls is None:
        return nn.Linear(in_f, out_f, bias_attr=False,
                         weight_attr=ParamAttr(initializer=Normal(0, 0.02)))
    if is_row:
        return cls(in_f, out_f, has_bias=False, input_is_parallel=True)
    return cls(in_f, out_f, has_bias=False, gather_output=False)


class LlamaAttention(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.num_heads = cfg.num_heads
        self.num_kv_heads = cfg.num_kv_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        col, row, _ = _linears(cfg)
        h = cfg.hidden_size
        kv = self.num_kv_heads * self.head_dim
        self.q_proj = _make_linear(col, h, h)
        self.k_proj = _make_linear(col, h, kv)
        self.v_proj = _make_linear(col, h, kv)
        self.o_proj = _make_linear(row, h, h, is_row=True)

    def forward(self, x):
        b, s, h = x.shape
        hd, nh, nkv = self.head_dim, self.num_heads, self.num_kv_heads
        q = ops.reshape(self.q_proj(x), [b, s, nh, hd])
        k = ops.reshape(self.k_proj(x), [b, s, nkv, hd])
        v = ops.reshape(self.v_proj(x), [b, s, nkv, hd])
        q = rotary_embedding(q, self.cfg.rope_theta)
        k = rotary_embedding(k, self.cfg.rope_theta)
        if nkv != nh:   # GQA: repeat kv heads
            rep = nh // nkv
            k = ops.reshape(
                ops.tile(ops.unsqueeze(k, 3), [1, 1, 1, rep, 1]),
                [b, s, nh, hd])
            v = ops.reshape(
                ops.tile(ops.unsqueeze(v, 3), [1, 1, 1, rep, 1]),
                [b, s, nh, hd])
        cp = self.cfg.context_parallel
        if cp == "ring":
            from ..distributed.fleet import ring_flash_attention
            out = ring_flash_attention(q, k, v, causal=True)
        elif cp == "ulysses":
            from ..distributed.fleet import scatter_gather_attention
            out = scatter_gather_attention(q, k, v, causal=True)
        elif self.cfg.use_flash_attention:
            out, _ = F.flash_attention(q, k, v, causal=True)
        else:
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        return self.o_proj(ops.reshape(out, [b, s, h]))


class LlamaMLP(nn.Layer):
    """SwiGLU MLP: down(silu(gate(x)) * up(x))."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        col, row, _ = _linears(cfg)
        h, ffn = cfg.hidden_size, cfg.intermediate_size
        self.gate_proj = _make_linear(col, h, ffn)
        self.up_proj = _make_linear(col, h, ffn)
        self.down_proj = _make_linear(row, ffn, h, is_row=True)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaBlock(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size,
                                          epsilon=cfg.rms_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                   epsilon=cfg.rms_eps)
        self.mlp = LlamaMLP(cfg)

    def forward(self, x):
        x = x + self.self_attn(self.input_layernorm(x))
        return x + self.mlp(self.post_attention_layernorm(x))


class LlamaModel(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        _, _, vemb = _linears(cfg)
        if vemb is not None:
            self.embed_tokens = vemb(cfg.vocab_size, cfg.hidden_size)
        else:
            self.embed_tokens = nn.Embedding(
                cfg.vocab_size, cfg.hidden_size,
                weight_attr=ParamAttr(initializer=Normal(0, 0.02)))
        self.layers = nn.LayerList([LlamaBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_eps)

    def forward(self, input_ids):
        x = self.embed_tokens(input_ids)
        for blk in self.layers:
            x = blk(x)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        if cfg.tie_embeddings:
            self.lm_head = None
        else:
            col, _, _ = _linears(cfg)
            # vocab-parallel head under TP: the [hidden, vocab] matrix is
            # the largest in the model and must shard over 'mp'
            self.lm_head = _make_linear(col, cfg.hidden_size,
                                        cfg.vocab_size)

    def forward(self, input_ids, labels=None):
        h = self.model(input_ids)
        if self.lm_head is None:
            logits = ops.matmul(h, self.model.embed_tokens.weight,
                                transpose_y=True)
        else:
            logits = self.lm_head(h)
        if labels is None:
            return logits
        v = logits.shape[-1]
        loss = F.cross_entropy(
            ops.reshape(logits[:, :-1, :], [-1, v]),
            ops.reshape(labels[:, 1:], [-1]))
        return logits, loss

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    @dispatch.no_grad()
    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0):
        """Greedy / temperature sampling without KV cache (full-context
        recompute per token — correct first, fast later)."""
        from ..core.generator import next_key
        import jax
        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(jnp.asarray(input_ids))
        for _ in range(max_new_tokens):
            logits = self(ids)
            last = logits[:, -1, :]
            if temperature > 0:
                arr = last._data / temperature
                nxt = jax.random.categorical(next_key(), arr, axis=-1)
            else:
                nxt = jnp.argmax(last._data, axis=-1)
            ids = ops.concat([ids, Tensor(nxt[:, None].astype(
                ids._data.dtype))], axis=1)
        return ids
