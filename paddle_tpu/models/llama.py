"""LLaMA-family decoder model — the hybrid-parallel north star.

Capability parity with the reference's LLaMA support (reference: the
fleet hybrid-parallel stack is exercised by PaddleNLP's LLaMA configs —
test/auto_parallel fixtures; RoPE/RMSNorm/SwiGLU ops in
paddle/phi/ops/yaml: rms_norm, swiglu, fused_rope). TPU-native: RoPE is a
fused jnp expression, attention is the Pallas flash kernel (or ring
attention over the sep axis for long context), GQA repeats KV heads inside
the kernel-feeding reshape, and mp_degree>1 builds the Megatron TP layers
so weights carry 'mp' shardings.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn, ops
from ..core import dispatch
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.parameter import ParamAttr


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 0            # 0 -> = num_heads (MHA); < heads = GQA
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-6
    use_flash_attention: bool = True
    tie_embeddings: bool = False
    mp_degree: int = 1
    sequence_parallel: bool = False
    context_parallel: str = ""       # "", "ring", "ulysses"
    recompute: bool = False          # activation-checkpoint every block
    #: fused lm-head + chunked streaming CE (forward returns (None, loss))
    fused_loss: bool = False

    def __post_init__(self):
        if self.num_kv_heads == 0:
            self.num_kv_heads = self.num_heads
        if self.num_heads % self.num_kv_heads:
            raise ValueError("num_heads must be divisible by num_kv_heads")
        if self.context_parallel not in ("", "ring", "ulysses"):
            raise ValueError(f"bad context_parallel "
                             f"{self.context_parallel!r}")


def llama_7b(**kw) -> LlamaConfig:
    return LlamaConfig(**kw)


def llama_tiny(**kw) -> LlamaConfig:
    kw.setdefault("vocab_size", 512)
    kw.setdefault("hidden_size", 128)
    kw.setdefault("intermediate_size", 256)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_seq_len", 128)
    return LlamaConfig(**kw)


def llama2_13b(**kw) -> LlamaConfig:
    kw.setdefault("hidden_size", 5120)
    kw.setdefault("intermediate_size", 13824)
    kw.setdefault("num_layers", 40)
    kw.setdefault("num_heads", 40)
    return LlamaConfig(**kw)


def llama2_70b(**kw) -> LlamaConfig:
    kw.setdefault("hidden_size", 8192)
    kw.setdefault("intermediate_size", 28672)
    kw.setdefault("num_layers", 80)
    kw.setdefault("num_heads", 64)
    kw.setdefault("num_kv_heads", 8)   # GQA
    return LlamaConfig(**kw)


def rope_rotate(a, theta, pos_offset):
    """The rope rotation on a [B, S, H, D] array — THE one copy of the
    (even, odd)-pair math: `rotary_embedding`'s lowering, the fused
    `rope_proj` composite (the rewrite's numerics reference), and the
    rope autotune probes all call this."""
    b, s, h, d = a.shape
    half = d // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32)
                             / half))
    off = jnp.asarray(pos_offset, jnp.float32)
    if off.ndim == 0:
        off = off[None]                        # (1,) broadcast over B
    positions = (off[:, None]
                 + jnp.arange(s, dtype=jnp.float32)[None, :])
    pos = positions[:, :, None] * freqs[None, None, :]
    cos = jnp.cos(pos)[:, :, None, :]          # (B|1, S, 1, half)
    sin = jnp.sin(pos)[:, :, None, :]
    x1, x2 = a[..., :half], a[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin],
        axis=-1).astype(a.dtype)


def rotary_embedding(x, theta: float = 10000.0, pos_offset=0):
    """Apply RoPE to [B, S, H, D] (reference fused_rope op). Pairs are the
    (even, odd) channel convention. ``pos_offset`` may be a python int, a
    traced scalar (cached decoding compiles one step for every position),
    or a per-batch ``(B,)`` vector (continuous-batching serving: every
    sequence in the batch sits at a different length)."""
    def f(a):
        return rope_rotate(a, theta, pos_offset)
    # static (python-int) offsets ride the IR record as semantic attrs
    # so compile/fusion can fold rope into the projection; traced /
    # per-batch offsets keep the op opaque (and unfusable), as before
    attrs = None
    if isinstance(pos_offset, int):
        attrs = {"theta": float(theta), "pos_offset": int(pos_offset)}

        def f(a, theta=float(theta), pos_offset=int(pos_offset),
              __f=f):
            return __f(a)
    return dispatch.call("rotary_embedding", f,
                         [x if isinstance(x, Tensor) else Tensor(x)],
                         attrs=attrs)


def _linears(cfg: LlamaConfig):
    if cfg.mp_degree > 1:
        from ..distributed import fleet
        if cfg.sequence_parallel:
            return (fleet.ColumnSequenceParallelLinear,
                    fleet.RowSequenceParallelLinear,
                    fleet.VocabParallelEmbedding)
        return (fleet.ColumnParallelLinear, fleet.RowParallelLinear,
                fleet.VocabParallelEmbedding)
    return None, None, None


def _make_linear(cls, in_f, out_f, is_row=False):
    if cls is None:
        return nn.Linear(in_f, out_f, bias_attr=False,
                         weight_attr=ParamAttr(initializer=Normal(0, 0.02)))
    if is_row:
        return cls(in_f, out_f, has_bias=False, input_is_parallel=True)
    return cls(in_f, out_f, has_bias=False, gather_output=False)


class LlamaAttention(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.num_heads = cfg.num_heads
        self.num_kv_heads = cfg.num_kv_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        col, row, _ = _linears(cfg)
        h = cfg.hidden_size
        kv = self.num_kv_heads * self.head_dim
        self.q_proj = _make_linear(col, h, h)
        self.k_proj = _make_linear(col, h, kv)
        self.v_proj = _make_linear(col, h, kv)
        self.o_proj = _make_linear(row, h, h, is_row=True)

    def forward(self, x, cache=None, pos: int = 0):
        b, s, h = x.shape
        hd, nh, nkv = self.head_dim, self.num_heads, self.num_kv_heads
        q = ops.reshape(self.q_proj(x), [b, s, nh, hd])
        k = ops.reshape(self.k_proj(x), [b, s, nkv, hd])
        v = ops.reshape(self.v_proj(x), [b, s, nkv, hd])
        q = rotary_embedding(q, self.cfg.rope_theta, pos_offset=pos)
        k = rotary_embedding(k, self.cfg.rope_theta, pos_offset=pos)
        if cache is not None:
            return self._cached_attention(x, q, k, v, cache, pos)
        if nkv != nh:   # GQA: repeat kv heads
            rep = nh // nkv
            k = ops.reshape(
                ops.tile(ops.unsqueeze(k, 3), [1, 1, 1, rep, 1]),
                [b, s, nh, hd])
            v = ops.reshape(
                ops.tile(ops.unsqueeze(v, 3), [1, 1, 1, rep, 1]),
                [b, s, nh, hd])
        cp = self.cfg.context_parallel
        if cp == "ring":
            from ..distributed.fleet import ring_flash_attention
            out = ring_flash_attention(q, k, v, causal=True)
        elif cp == "ulysses":
            from ..distributed.fleet import scatter_gather_attention
            out = scatter_gather_attention(q, k, v, causal=True)
        elif self.cfg.use_flash_attention:
            out, _ = F.flash_attention(q, k, v, causal=True)
        else:
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        return self.o_proj(ops.reshape(out, [b, s, h]))

    def _cached_attention(self, x, q, k, v, cache, pos: int):
        """Decode-time attention against the KV cache (reference cached
        decoding in fused_multi_transformer): writes this step's K/V at
        ``pos`` and attends the query over all cached positions <= its
        global position. Returns (out, new_cache)."""
        import jax
        b, s, h = x.shape
        nh, nkv, hd = self.num_heads, self.num_kv_heads, self.head_dim
        scale = 1.0 / math.sqrt(hd)

        def f(qa, ka, va, kc, vc):
            zero = jnp.asarray(0, jnp.int32)
            p0 = jnp.asarray(pos, jnp.int32)
            kc = jax.lax.dynamic_update_slice(kc, ka,
                                              (zero, p0, zero, zero))
            vc = jax.lax.dynamic_update_slice(vc, va,
                                              (zero, p0, zero, zero))
            kk, vv = kc, vc
            if nkv != nh:
                rep = nh // nkv
                kk = jnp.repeat(kc, rep, axis=2)
                vv = jnp.repeat(vc, rep, axis=2)
            logits = jnp.einsum("bqhd,bkhd->bhqk", qa,
                                kk).astype(jnp.float32) * scale
            total = kk.shape[1]
            kpos = jnp.arange(total)[None, None, None, :]
            qpos = (p0 + jnp.arange(s))[None, None, :, None]
            logits = jnp.where(kpos <= qpos, logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1).astype(qa.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, vv)
            return out.reshape(b, s, nh * hd), kc, vc

        out, kc, vc = dispatch.call(
            "llama_cached_attention", f,
            [q, k, v, Tensor(cache["k"]), Tensor(cache["v"])])
        return self.o_proj(out), {"k": kc._data, "v": vc._data}


class LlamaMLP(nn.Layer):
    """SwiGLU MLP: down(silu(gate(x)) * up(x))."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        col, row, _ = _linears(cfg)
        h, ffn = cfg.hidden_size, cfg.intermediate_size
        self.gate_proj = _make_linear(col, h, ffn)
        self.up_proj = _make_linear(col, h, ffn)
        self.down_proj = _make_linear(row, ffn, h, is_row=True)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaBlock(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size,
                                          epsilon=cfg.rms_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                   epsilon=cfg.rms_eps)
        self.mlp = LlamaMLP(cfg)

    def forward(self, x, cache=None, pos: int = 0):
        if cache is not None:
            att, new_cache = self.self_attn(self.input_layernorm(x),
                                            cache=cache, pos=pos)
            x = x + att
            return x + self.mlp(self.post_attention_layernorm(x)), \
                new_cache
        x = x + self.self_attn(self.input_layernorm(x))
        return x + self.mlp(self.post_attention_layernorm(x))


class LlamaModel(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        _, _, vemb = _linears(cfg)
        if vemb is not None:
            self.embed_tokens = vemb(cfg.vocab_size, cfg.hidden_size)
        else:
            self.embed_tokens = nn.Embedding(
                cfg.vocab_size, cfg.hidden_size,
                weight_attr=ParamAttr(initializer=Normal(0, 0.02)))
        self.layers = nn.LayerList([LlamaBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_eps)

    def forward(self, input_ids):
        x = self.embed_tokens(input_ids)
        if self.cfg.recompute:
            from ._remat import remat_block
            for blk in self.layers:
                x = remat_block(blk, x)
        else:
            for blk in self.layers:
                x = blk(x)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        if cfg.tie_embeddings:
            self.lm_head = None
        else:
            col, _, _ = _linears(cfg)
            # vocab-parallel head under TP: the [hidden, vocab] matrix is
            # the largest in the model and must shard over 'mp'
            self.lm_head = _make_linear(col, cfg.hidden_size,
                                        cfg.vocab_size)

    def forward(self, input_ids, labels=None):
        h = self.model(input_ids)
        if labels is not None and self.cfg.fused_loss:
            hh = ops.reshape(h[:, :-1, :], [-1, self.cfg.hidden_size])
            lab = ops.reshape(labels[:, 1:], [-1])
            if self.lm_head is None:
                loss = F.fused_linear_cross_entropy(
                    hh, self.model.embed_tokens.weight, lab,
                    transpose_y=True)
            else:
                loss = F.fused_linear_cross_entropy(
                    hh, self.lm_head.weight, lab)
            return None, loss
        if self.lm_head is None:
            logits = ops.matmul(h, self.model.embed_tokens.weight,
                                transpose_y=True)
        else:
            logits = self.lm_head(h)
        if labels is None:
            return logits
        v = logits.shape[-1]
        loss = F.cross_entropy(
            ops.reshape(logits[:, :-1, :], [-1, v]),
            ops.reshape(labels[:, 1:], [-1]))
        return logits, loss

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    @dispatch.no_grad()
    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, use_cache: bool = True):
        """Autoregressive decode. ``use_cache=True`` (default) runs a
        KV-cached jitted decode loop — prefill once, then one [B, 1] step
        per token against the cache (reference: the fused_multi_transformer
        cached-decoding path); ``use_cache=False`` recomputes the full
        context every token (numerics ground truth)."""
        from ..core.generator import next_key
        import jax
        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(jnp.asarray(input_ids))
        # identical RNG contract on both paths: greedy consumes no keys;
        # sampling pre-splits one stream of per-token keys
        keys = (jax.random.split(next_key(), max_new_tokens)
                if temperature > 0 else
                jnp.zeros((max_new_tokens, 2), jnp.uint32))
        if not use_cache:
            for i in range(max_new_tokens):
                logits = self(ids)
                last = logits[:, -1, :]
                if temperature > 0:
                    nxt = jax.random.categorical(
                        keys[i], last._data / temperature, axis=-1)
                else:
                    nxt = jnp.argmax(last._data, axis=-1)
                ids = ops.concat([ids, Tensor(nxt[:, None].astype(
                    ids._data.dtype))], axis=1)
            return ids
        return self._generate_cached(ids, max_new_tokens, temperature,
                                     keys)

    def _decode_logits(self, token_arr, cache, pos: int):
        """One cached step: token_arr [B, t]; returns (last-token logits,
        new cache) — traced under jit by _generate_cached."""
        h = self.model.embed_tokens(Tensor(token_arr))
        new_cache = []
        for li, blk in enumerate(self.model.layers):
            h, c = blk(h, cache=cache[li], pos=pos)
            new_cache.append(c)
        h = self.model.norm(h)
        if self.lm_head is None:
            logits = ops.matmul(h, self.model.embed_tokens.weight,
                                transpose_y=True)
        else:
            logits = self.lm_head(h)
        return logits._data[:, -1, :], new_cache

    def _generate_cached(self, ids: Tensor, max_new_tokens: int,
                         temperature: float, keys):
        import jax
        cfg = self.cfg
        b, prompt_len = ids.shape
        total = prompt_len + max_new_tokens
        hd = cfg.hidden_size // cfg.num_heads
        cache = [
            {"k": jnp.zeros((b, total, cfg.num_kv_heads, hd), jnp.float32),
             "v": jnp.zeros((b, total, cfg.num_kv_heads, hd), jnp.float32)}
            for _ in range(cfg.num_layers)]
        params = list(self.parameters())

        def with_params(fn):
            def wrapped(pa, *args):
                originals = [p._data for p in params]
                for p, a in zip(params, pa):
                    p._data = a
                try:
                    return fn(*args)
                finally:
                    for p, o in zip(params, originals):
                        p._data = o
            return wrapped

        # ONE compiled program: prefill + a lax.scan over decode steps
        # (pos is a traced scalar; the cache lives in the scan carry, so
        # there is a single device dispatch for the whole generation)
        tok_dtype = ids._data.dtype

        def decode_all(prompt, cache_, keys):
            logits, cache_ = self._decode_logits(prompt, cache_, 0)

            def body(carry, key):
                logits, cache_, pos = carry
                if temperature > 0:
                    nxt = jax.random.categorical(
                        key, logits / temperature, axis=-1)
                else:
                    nxt = jnp.argmax(logits, axis=-1)
                logits, cache_ = self._decode_logits(
                    nxt[:, None].astype(tok_dtype), cache_, pos)
                return (logits, cache_, pos + 1), nxt

            init = (logits, cache_, jnp.asarray(prompt_len, jnp.int32))
            (_, _, _), new_toks = jax.lax.scan(body, init, keys)
            return jnp.swapaxes(new_toks, 0, 1).astype(tok_dtype)  # [B, n]

        if not hasattr(self, "_decode_jit"):
            self._decode_jit = {}
        # the concrete temperature is baked into the compiled body, so it
        # must key the cache; cap the cache (serving with many distinct
        # prompt lengths should bucket/pad prompts instead)
        jit_key = (b, prompt_len, max_new_tokens, float(temperature))
        fn = self._decode_jit.get(jit_key)
        if fn is None:
            if len(self._decode_jit) >= 16:
                self._decode_jit.pop(next(iter(self._decode_jit)))
            fn = jax.jit(with_params(decode_all))
            self._decode_jit[jit_key] = fn

        pa = [p._data for p in params]
        new_toks = fn(pa, ids._data, cache, keys)
        return Tensor(jnp.concatenate([ids._data, new_toks], axis=1))
