"""Shared activation-checkpoint helper for the model zoo.

TPU-native recompute: under a jax trace (a jitted training step,
``jax.value_and_grad`` over the model — the steady-state path) each
transformer block is wrapped in ``jax.checkpoint`` so only the
block-boundary activation is a backward residual; the interior
(attention scores, MLP intermediate) is rematerialized during the
backward pass. That trades ~1/3 extra FLOPs for the activation HBM that
otherwise caps model size on a 16 GB chip. In eager mode the tape-level
``fleet.recompute`` PyLayer provides the same contract (reference:
python/paddle/distributed/fleet/recompute/recompute.py).
"""
from __future__ import annotations

import jax

from ..core import dispatch
from ..core.tensor import Tensor


def remat_block(blk, *args):
    """Run ``blk(*args)`` (Tensor -> Tensor) with activation checkpointing.

    ``blk`` is typically a Layer; extra Tensor args (e.g. an attention
    mask) ride along and are saved as residuals, not rematerialized.
    """
    datas = [a._data for a in args]
    if any(isinstance(d, jax.core.Tracer) for d in datas):
        def f(*arrs):
            return blk(*[Tensor(a) for a in arrs])._data
        return Tensor(jax.checkpoint(f)(*datas), stop_gradient=False)
    if not dispatch.grad_enabled():
        return blk(*args)
    from ..distributed.fleet.recompute import recompute
    return recompute(blk, *args)
