"""GPT-2 style decoder-only transformer — the flagship model.

Capability parity with the reference's GPT fixture
(reference: test/auto_parallel/get_gpt_model.py; PaddleNLP GPT uses the same
fleet TP layers). TPU-native: attention is the flash-attention functional
(Pallas kernel on TPU), all math is bf16-friendly, and the model can be
constructed tensor-parallel (mp_degree > 1) using the Megatron-style
parallel layers from paddle_tpu.distributed.fleet — weights then carry
NamedShardings over the 'mp' mesh axis and XLA inserts the collectives.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

from .. import nn
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.parameter import ParamAttr
from .. import ops


def _init_attr(std=0.02):
    """GPT-2 init: N(0, 0.02), residual projections scaled by depth."""
    return ParamAttr(initializer=Normal(0.0, std))


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    intermediate_size: int = 0      # 0 -> 4*hidden
    dropout: float = 0.0
    use_flash_attention: bool = True
    mp_degree: int = 1              # tensor-parallel ways ('mp' mesh axis)
    sequence_parallel: bool = False
    #: activation-checkpoint every block (reference recompute pass) —
    #: required to train the 345M+ rungs on a 16 GB chip
    recompute: bool = False
    #: fuse the lm-head matmul into the loss (chunked streaming CE; the
    #: full (B*S, V) logits tensor is never materialized). forward()
    #: then returns (None, loss) when labels are given.
    fused_loss: bool = False
    #: long-context attention backend over the 'sep' axis:
    #: "" (dense/flash local), "ring" (ring attention), "ulysses"
    #: (all-to-all head-scatter) — see fleet.meta_parallel.sep_utils
    context_parallel: str = ""

    def __post_init__(self):
        if self.intermediate_size == 0:
            self.intermediate_size = 4 * self.hidden_size
        if self.context_parallel not in ("", "ring", "ulysses"):
            raise ValueError(
                f"context_parallel must be '', 'ring' or 'ulysses', got "
                f"{self.context_parallel!r}")
        if self.context_parallel == "ring" and self.dropout > 0:
            raise ValueError(
                "attention dropout is not supported with ring attention "
                "(the probability mask is never materialized globally); "
                "set dropout=0 or use context_parallel='ulysses'")


def gpt2_small(**kw) -> "GPTConfig":
    return GPTConfig(**kw)


def gpt2_medium(**kw) -> "GPTConfig":
    kw.setdefault("hidden_size", 1024)
    kw.setdefault("num_layers", 24)
    kw.setdefault("num_heads", 16)
    return GPTConfig(**kw)


def _linears(cfg: GPTConfig):
    """Pick (column, row, vocab-embedding) layer classes by mp_degree."""
    if cfg.mp_degree > 1:
        from ..distributed import fleet
        if cfg.sequence_parallel:
            col = fleet.ColumnSequenceParallelLinear
            row = fleet.RowSequenceParallelLinear
        else:
            col = fleet.ColumnParallelLinear
            row = fleet.RowParallelLinear
        return col, row, fleet.VocabParallelEmbedding
    return None, None, None


class GPTAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        self.use_flash = cfg.use_flash_attention
        self.dropout = cfg.dropout
        self.context_parallel = cfg.context_parallel
        col, row, _ = _linears(cfg)
        h = cfg.hidden_size
        if col is not None:
            self.qkv_proj = col(h, 3 * h, has_bias=True, gather_output=False)
            self.out_proj = row(h, h, has_bias=True, input_is_parallel=True)
        else:
            self.qkv_proj = nn.Linear(h, 3 * h, weight_attr=_init_attr())
            self.out_proj = nn.Linear(
                h, h, weight_attr=_init_attr(0.02 / math.sqrt(2 * cfg.num_layers)))

    def forward(self, x):
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        # local width under TP: heads split across mp ranks is expressed by
        # the sharded last dim; global semantics keep shape (b, s, 3h)
        q, k, v = ops.split(qkv, 3, axis=-1)
        q = ops.reshape(q, [b, s, self.num_heads, self.head_dim])
        k = ops.reshape(k, [b, s, self.num_heads, self.head_dim])
        v = ops.reshape(v, [b, s, self.num_heads, self.head_dim])
        if self.context_parallel == "ring":
            from ..distributed.fleet import ring_flash_attention
            out = ring_flash_attention(q, k, v, causal=True)
        elif self.context_parallel == "ulysses":
            from ..distributed.fleet import scatter_gather_attention
            out = scatter_gather_attention(
                q, k, v, causal=True,
                dropout_p=self.dropout if self.training else 0.0)
        elif self.use_flash:
            out, _ = F.flash_attention(q, k, v, dropout=self.dropout,
                                       causal=True, training=self.training)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=self.dropout,
                training=self.training)
        out = ops.reshape(out, [b, s, h])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        col, row, _ = _linears(cfg)
        h, ffn = cfg.hidden_size, cfg.intermediate_size
        if col is not None:
            self.fc1 = col(h, ffn, has_bias=True, gather_output=False)
            self.fc2 = row(ffn, h, has_bias=True, input_is_parallel=True)
        else:
            self.fc1 = nn.Linear(h, ffn, weight_attr=_init_attr())
            self.fc2 = nn.Linear(
                ffn, h, weight_attr=_init_attr(0.02 / math.sqrt(2 * cfg.num_layers)))

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        self.mlp = GPTMLP(cfg)
        self.dropout = cfg.dropout

    def forward(self, x):
        y = self.attn(self.ln1(x))
        if self.dropout > 0:
            y = F.dropout(y, p=self.dropout, training=self.training)
        x = x + y
        y = self.mlp(self.ln2(x))
        if self.dropout > 0:
            y = F.dropout(y, p=self.dropout, training=self.training)
        return x + y


class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        _, _, vocab_emb = _linears(cfg)
        if vocab_emb is not None:
            self.wte = vocab_emb(cfg.vocab_size, cfg.hidden_size)
        else:
            self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                    weight_attr=_init_attr())
        self.wpe = nn.Embedding(cfg.max_seq_len, cfg.hidden_size,
                                weight_attr=_init_attr())
        self.blocks = nn.LayerList([GPTBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)

    def forward(self, input_ids):
        b, s = input_ids.shape
        pos = ops.arange(0, s, dtype="int64")
        x = self.wte(input_ids) + self.wpe(pos)
        if self.cfg.recompute:
            from ._remat import remat_block
            for blk in self.blocks:
                x = remat_block(blk, x)
        else:
            for blk in self.blocks:
                x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    """LM head ties to wte; loss = next-token cross entropy."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)

    def forward(self, input_ids, labels=None):
        h = self.gpt(input_ids)
        if labels is not None and self.cfg.fused_loss:
            loss = F.fused_linear_cross_entropy(
                ops.reshape(h[:, :-1, :], [-1, self.cfg.hidden_size]),
                self.gpt.wte.weight,
                ops.reshape(labels[:, 1:], [-1]), transpose_y=True)
            return None, loss
        logits = ops.matmul(h, self.gpt.wte.weight, transpose_y=True)
        if labels is None:
            return logits
        v = logits.shape[-1]
        loss = F.cross_entropy(
            ops.reshape(logits[:, :-1, :], [-1, v]),
            ops.reshape(labels[:, 1:], [-1]))
        return logits, loss

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    def flops_per_token(self) -> float:
        """Dense training FLOPs/token ~= 6*N + attention term
        (per the scaling-book accounting: fwd 2N, bwd 4N, attention
        12*L*h*s for fwd+bwd)."""
        c = self.cfg
        n = self.num_params()
        attn = 12 * c.num_layers * c.hidden_size * c.max_seq_len
        return 6 * n + attn
