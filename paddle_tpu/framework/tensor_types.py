"""Auxiliary tensor containers: TensorArray, SelectedRows.

Capability parity with the reference container tensor types (reference:
paddle/phi/core/tensor_array.h TensorArray — dynamic list of tensors fed
by while_loop/array_write; paddle/phi/core/selected_rows.h SelectedRows —
(rows, value) pairs holding sparse gradient slices for embeddings).
TPU-native: TensorArray is a Python list facade whose ``stack`` produces
one jnp array (inside scans, jax carries the stacked form directly);
SelectedRows keeps (rows, values) and scatters into dense on demand.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor, as_tensor


class TensorArray:
    """reference tensor_array.h — write/read by index, stack/concat."""

    def __init__(self, values: Optional[Sequence[Tensor]] = None):
        self._items: List[Optional[Tensor]] = list(values or [])

    def write(self, index: int, value) -> "TensorArray":
        value = value if isinstance(value, Tensor) else as_tensor(value)
        while len(self._items) <= index:
            self._items.append(None)
        self._items[index] = value
        return self

    append = lambda self, v: self.write(len(self._items), v)

    def read(self, index: int) -> Tensor:
        v = self._items[index]
        if v is None:
            raise IndexError(f"TensorArray slot {index} never written")
        return v

    def __len__(self):
        return len(self._items)

    def __getitem__(self, i):
        return self.read(i)

    def _all_written(self):
        holes = [i for i, t in enumerate(self._items) if t is None]
        if holes:
            raise ValueError(
                f"TensorArray slots {holes} were never written; stack/"
                "concat over a sparse array would misalign indices")
        return self._items

    def stack(self, axis: int = 0) -> Tensor:
        return dispatch.call(
            "tensor_array_stack",
            lambda *xs: jnp.stack(xs, axis=axis), self._all_written())

    def concat(self, axis: int = 0) -> Tensor:
        return dispatch.call(
            "tensor_array_concat",
            lambda *xs: jnp.concatenate(xs, axis=axis),
            self._all_written())


class SelectedRows:
    """reference selected_rows.h — sparse row-slice gradient container."""

    def __init__(self, rows, value, height: int):
        self.rows = jnp.asarray(
            rows._data if isinstance(rows, Tensor) else rows)
        self.value = value if isinstance(value, Tensor) else as_tensor(
            value)
        self.height = int(height)

    def to_dense(self) -> Tensor:
        rows, height = self.rows, self.height

        def f(vals):
            out = jnp.zeros((height,) + vals.shape[1:], vals.dtype)
            return out.at[rows].add(vals)
        return dispatch.call("selected_rows_to_dense", f, [self.value])

    def merge(self) -> "SelectedRows":
        """Merge duplicate rows (reference merge_selected_rows op)."""
        uniq, inv = jnp.unique(self.rows, return_inverse=True,
                               size=self.rows.shape[0],
                               fill_value=self.height)

        def f(vals):
            out = jnp.zeros((uniq.shape[0],) + vals.shape[1:], vals.dtype)
            return out.at[inv].add(vals)
        merged = dispatch.call("merge_selected_rows", f, [self.value])
        keep = uniq < self.height
        return SelectedRows(uniq[keep],
                            Tensor(merged._data[keep]), self.height)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={int(self.rows.shape[0])})")


__all__ = ["TensorArray", "SelectedRows"]
