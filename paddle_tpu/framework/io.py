"""Single-process save/load — atomic, verified checkpoints.

Reference: python/paddle/framework/io.py (save:743 / load:985 — the
reference chunks large pickles to dodge the 4 GB single-bytes limits of
old protocols and pins the pickle protocol). TPU-native format: the
pickled structure stays SMALL — every array above a threshold is replaced
by an indexed placeholder and its bytes are streamed to the same file in
fixed-size chunks after the pickle blob, so a multi-GB state_dict never
materializes a second copy in memory and no pickle frame approaches the
4 GB limits regardless of protocol. bfloat16 arrays round-trip natively
(ml_dtypes numpy dtype).

Durability contract (format v2):

- **Atomic publish** — ``save`` writes to a same-directory temp file,
  flushes + fsyncs it, then ``os.replace``\\ s onto the destination and
  fsyncs the directory. A crash at ANY instant leaves the destination
  either absent or holding the complete previous checkpoint — never a
  torn file.
- **Verified load** — the v2 footer carries a CRC32 per raw segment, a
  CRC32 of the pickle blob, and a whole-blob digest over everything
  before the footer; ``load(path, verify=True)`` (the default) detects
  truncation and bit-rot with a :class:`CheckpointCorruptError` naming
  the offending section (``header`` / ``pickle`` / ``segment i ('key')``
  / ``footer`` / ``trailer``).

Layout (v2): ``magic2 | u64 pickle_len | pickle | raw segments… | footer
pickle | u64 footer_off | u64 footer_len | u32 footer_crc | end-magic``.
The footer maps placeholder index -> (offset, nbytes, dtype, shape, crc)
plus the key path of each segment for precise corruption reports. Legacy
v1 (``PTCKPT01``) and round-2 plain-pickle files still load (with
structural bounds validation instead of checksums — v1 carries none).
"""
from __future__ import annotations

import contextlib
import os
import pickle
import struct
import time
import zlib

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor
from ..fault import inject as _inject
from ..observability import metrics as _metrics

_BF16_TAG = "__bf16__"
_EXT_TAG = "__ext_seg__"
_MAGIC = b"PTCKPT01"            # legacy v1: no checksums
_MAGIC2 = b"PTCKPT02"           # v2: per-segment CRC32 + whole-blob digest
_END_MAGIC = b"PTCKEND2"
_TRAILER = struct.Struct("<QQI")  # footer_off, footer_len, footer_crc
_SEG_THRESHOLD = 1 << 20        # arrays >= 1 MB stream as raw segments
_CHUNK = 64 << 20               # 64 MB write/read granularity

_m_save_seconds = _metrics.histogram(
    "paddle_tpu_ckpt_save_seconds", "Wall time of framework.io.save.")
_m_save_bytes = _metrics.counter(
    "paddle_tpu_ckpt_save_bytes_total", "Bytes written by framework.io.save.")
_m_load_seconds = _metrics.histogram(
    "paddle_tpu_ckpt_load_seconds", "Wall time of framework.io.load.")
_m_corruption = _metrics.counter(
    "paddle_tpu_ckpt_corruption_detected_total",
    "Checkpoint loads rejected by integrity checking, per section.",
    labelnames=("section",))


class CheckpointCorruptError(ValueError):
    """A checkpoint failed structural or checksum validation. ``section``
    names the damaged region precisely enough to tell truncation (trailer/
    segment bounds) from bit-rot (checksum mismatch)."""

    def __init__(self, path, section, detail):
        self.path = str(path)
        self.section = section
        self.detail = detail
        super().__init__(
            f"corrupt checkpoint {str(path)!r}: {section}: {detail}")

    def __reduce__(self):
        # Exception.__reduce__ would replay args=(message,) into the
        # 3-arg __init__ and break crossing process boundaries
        return (type(self), (self.path, self.section, self.detail))


def _corrupt(path, section, detail) -> CheckpointCorruptError:
    """Count the detection and build the error (metric lives at the
    raise site, not in the constructor, so unpickling a propagated error
    never double-counts)."""
    _m_corruption.inc(section=section.split(" ")[0])
    return CheckpointCorruptError(path, section, detail)


def _to_numpy(arr) -> np.ndarray:
    return np.asarray(arr)


def _pack(obj, segments, names, prefix=""):
    if isinstance(obj, Tensor):
        obj = obj._data
        # fall through: payloads serialize as arrays, tagged for rehydrate
        arr = _to_numpy(obj)
        if arr.nbytes >= _SEG_THRESHOLD:  # tpulint: disable=TPU105 — checkpoint save IS the host boundary: segment layout keys on the materialized payload's byte size, there is nothing to keep on device
            segments.append(arr)
            names.append(prefix or f"<segment {len(segments) - 1}>")
            return {_EXT_TAG: len(segments) - 1, "tensor": True}
        return {"__tensor__": True, "data": arr}
    if isinstance(obj, (jnp.ndarray, np.ndarray)) and not np.isscalar(obj):  # tpulint: disable=TPU104,TPU105 — serialization type-walk over an already-host-bound state dict (np.isscalar reads type, not data); host by design
        arr = _to_numpy(obj)
        if arr.nbytes >= _SEG_THRESHOLD:  # tpulint: disable=TPU105 — same segment-layout host boundary as the Tensor branch above
            segments.append(arr)
            names.append(prefix or f"<segment {len(segments) - 1}>")
            return {_EXT_TAG: len(segments) - 1, "tensor": False}
        return arr
    if isinstance(obj, dict):
        return {k: _pack(v, segments, names,
                         f"{prefix}.{k}" if prefix else str(k))
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v, segments, names, f"{prefix}[{i}]")
                 for i, v in enumerate(obj))
    return obj


def _rehydrate_array(arr: np.ndarray, as_tensor: bool):
    # every loaded array rehydrates as Tensor regardless of segment size —
    # the load contract must not depend on the save-side threshold
    del as_tensor
    return Tensor(jnp.asarray(arr))


def _unpack(obj, seg_arrays):
    if isinstance(obj, dict):
        if _EXT_TAG in obj:
            return _rehydrate_array(seg_arrays[obj[_EXT_TAG]],
                                    obj.get("tensor", True))
        if obj.get(_BF16_TAG):  # legacy round-2 bf16 encoding
            return Tensor(jnp.asarray(obj["data"]).astype(dtypes.bfloat16))
        if obj.get("__tensor__"):
            return Tensor(jnp.asarray(obj["data"]))
        return {k: _unpack(v, seg_arrays) for k, v in obj.items()}
    if isinstance(obj, np.ndarray):
        return Tensor(jnp.asarray(obj))
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, seg_arrays) for v in obj)
    return obj


class _CheckedWriter:
    """Write-through wrapper that maintains the whole-blob digest and a
    resettable per-region CRC, and honors the
    ``io.write_truncate_after_bytes`` fault point: once the armed byte
    budget is exhausted the writer persists only the prefix that fits and
    raises — the torn temp file this leaves behind is exactly what a crash
    or full disk produces, which the atomic-publish path must survive."""

    def __init__(self, f):
        self._f = f
        self.digest = 0
        self.region_crc = 0
        self.written = 0
        params = _inject.peek("io.write_truncate_after_bytes")
        self._truncate_after = None if params is None else \
            int(params.get("after_bytes", 0))

    def begin_region(self):
        self.region_crc = 0

    def write(self, data):
        data = memoryview(data)
        if self._truncate_after is not None and \
                self.written + len(data) > self._truncate_after:
            keep = max(self._truncate_after - self.written, 0)
            if keep:
                self._f.write(data[:keep])
                self.written += keep
            self._f.flush()
            _inject.fire("io.write_truncate_after_bytes")
            raise _inject.InjectedFault(
                "io.write_truncate_after_bytes",
                f"write truncated after {self.written} bytes")
        self._f.write(data)
        self.digest = zlib.crc32(data, self.digest)
        self.region_crc = zlib.crc32(data, self.region_crc)
        self.written += len(data)

    def tell(self):
        return self._f.tell()


def _write_segment(w: _CheckedWriter, arr: np.ndarray) -> tuple:
    offset = w.tell()
    w.begin_region()
    view = memoryview(np.ascontiguousarray(arr).reshape(-1).view(np.uint8))
    for pos in range(0, len(view), _CHUNK):
        w.write(view[pos:pos + _CHUNK])
    if not len(view):
        w.write(b"")
    return (offset, arr.nbytes, str(arr.dtype), tuple(arr.shape),
            w.region_crc)


def _read_segment(f, offset, nbytes, dtype, shape, want_crc=True):
    """Read one raw segment; returns (array, crc32-of-bytes or 0 when
    ``want_crc`` is off — verify=False must not pay for checksums)."""
    out = np.empty(int(np.prod(shape)) if shape else 1, np.dtype(dtype))
    buf = out.view(np.uint8).reshape(-1)
    f.seek(offset)
    pos = 0
    crc = 0
    while pos < nbytes:
        n = f.readinto(memoryview(buf)[pos:pos + _CHUNK])
        if not n:
            raise EOFError(f"truncated checkpoint segment at {offset}")
        if want_crc:
            crc = zlib.crc32(memoryview(buf)[pos:pos + n], crc)
        pos += n
    return out.reshape(shape), crc


def _fsync_dir(dirname):
    """Durably record the rename in the directory (POSIX crash-consistency
    contract); best-effort on platforms without directory fds."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_replace(tmp: str, dst: str):
    """The shared publish step of every atomic write in the framework:
    ``io.rename_fail`` guard → ``os.replace`` → directory fsync. Using
    one helper keeps the durability and fault-injection behavior uniform
    across framework.io, the distributed checkpoint, and the manager
    manifest."""
    _inject.check("io.rename_fail", exc=OSError)
    os.replace(tmp, dst)
    _fsync_dir(os.path.dirname(dst))


@contextlib.contextmanager
def atomic_file(dst: str, tmp_suffix: str = ""):
    """Yield a same-directory temp path; on clean exit publish it onto
    ``dst`` via :func:`atomic_replace`, on ANY error unlink it and
    re-raise. The caller writes + fsyncs the temp file inside the block
    (``tmp_suffix`` accommodates writers that dictate an extension, e.g.
    ``np.savez``)."""
    tmp = f"{dst}.tmp.{os.getpid()}{tmp_suffix}"
    try:
        yield tmp
        atomic_replace(tmp, dst)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save(obj, path, protocol=4, **configs):
    """Persist ``obj`` (state_dict / nested containers / Tensors)
    atomically: temp file → flush/fsync → ``os.replace`` → directory
    fsync. The destination never holds a torn checkpoint.

    ``protocol`` is pinned to the 2..5 range (reference io.py contract);
    large arrays bypass pickle entirely, so any allowed protocol handles
    arbitrarily large checkpoints.
    """
    if not 2 <= int(protocol) <= pickle.HIGHEST_PROTOCOL:
        raise ValueError(
            f"pickle protocol must be in [2, {pickle.HIGHEST_PROTOCOL}], "
            f"got {protocol}")
    path = str(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    segments, names = [], []
    packed = _pack(obj, segments, names)
    blob = pickle.dumps(packed, protocol=int(protocol))
    t0 = time.perf_counter()
    with atomic_file(path) as tmp:
        with open(tmp, "wb") as raw:
            w = _CheckedWriter(raw)
            w.write(_MAGIC2)
            w.write(struct.pack("<Q", len(blob)))
            w.write(blob)
            pickle_crc = zlib.crc32(blob)
            index = [_write_segment(w, arr) for arr in segments]
            footer = pickle.dumps(
                {"format": 2, "index": index, "seg_names": names,
                 "pickle_crc": pickle_crc, "digest": w.digest},
                protocol=int(protocol))
            footer_off = w.tell()
            w.write(footer)
            w.write(_TRAILER.pack(footer_off, len(footer),
                                  zlib.crc32(footer)))
            w.write(_END_MAGIC)
            total = w.written
            raw.flush()
            _inject.check("io.fsync_fail", exc=OSError)
            os.fsync(raw.fileno())
    _m_save_seconds.observe(time.perf_counter() - t0)
    _m_save_bytes.inc(total)


def load(path, verify=True, **configs):
    """Load a checkpoint. ``verify=True`` (default) checks the v2 footer
    CRC, the pickle-blob CRC, every segment CRC, and the whole-blob
    digest, raising :class:`CheckpointCorruptError` that names the
    damaged section. Structural bounds are validated in every mode and
    for every format, so truncated files fail with a clear error instead
    of ``struct.error``/``EOFError``."""
    path = str(path)
    t0 = time.perf_counter()
    with open(path, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        magic = f.read(len(_MAGIC2))
        if magic == _MAGIC2:
            out = _load_v2(f, size, path, verify)
        elif magic == _MAGIC:
            out = _load_v1(f, size, path)
        else:
            out = _load_legacy(f, size, path)
    _m_load_seconds.observe(time.perf_counter() - t0)
    return out


def _load_v2(f, size, path, verify):
    header_len = len(_MAGIC2) + 8
    trailer_len = _TRAILER.size + len(_END_MAGIC)
    if size < header_len + trailer_len:
        raise _corrupt(
            path, "trailer", f"file is {size} bytes — truncated below the "
            f"minimum v2 layout ({header_len + trailer_len} bytes)")
    (blob_len,) = struct.unpack("<Q", f.read(8))
    if header_len + blob_len > size - trailer_len:
        raise _corrupt(
            path, "pickle", f"pickle length {blob_len} exceeds file bounds "
            f"(file is {size} bytes) — truncated or corrupt header")
    blob = f.read(blob_len)
    f.seek(size - trailer_len)
    trailer = f.read(_TRAILER.size)
    if f.read(len(_END_MAGIC)) != _END_MAGIC:
        raise _corrupt(
            path, "trailer", "end marker missing — file truncated "
            "mid-write or trailing bytes corrupted")
    footer_off, footer_len, footer_crc = _TRAILER.unpack(trailer)
    if footer_off < header_len + blob_len or \
            footer_off + footer_len != size - trailer_len:
        raise _corrupt(
            path, "footer", f"footer bounds (offset={footer_off}, "
            f"length={footer_len}) inconsistent with file size {size}")
    f.seek(footer_off)
    footer_bytes = f.read(footer_len)
    if zlib.crc32(footer_bytes) != footer_crc:
        raise _corrupt(path, "footer", "checksum mismatch")
    try:
        meta = pickle.loads(footer_bytes)
        index = meta["index"]
        seg_names = meta.get("seg_names", [])
    except Exception as e:
        raise _corrupt(
            path, "footer", f"undecodable footer: {e}") from e
    if verify and zlib.crc32(blob) != meta["pickle_crc"]:
        raise _corrupt(path, "pickle", "checksum mismatch")
    try:
        packed = pickle.loads(blob)
    except Exception as e:
        raise _corrupt(
            path, "pickle", f"undecodable pickle blob: {e}") from e
    digest = zlib.crc32(blob, zlib.crc32(
        _MAGIC2 + struct.pack("<Q", blob_len))) if verify else 0
    seg_arrays = []
    for i, entry in enumerate(index):
        offset, nbytes, dtype, shape, crc = entry
        name = seg_names[i] if i < len(seg_names) else f"<segment {i}>"
        label = f"segment {i} ({name!r})"
        if offset + nbytes > footer_off:
            raise _corrupt(
                path, label, f"segment bounds (offset={offset}, "
                f"nbytes={nbytes}) overrun the data region — truncated "
                "or corrupt footer")
        try:
            arr, got_crc = _read_segment(f, offset, nbytes, dtype, shape,
                                         want_crc=verify)
        except (EOFError, OSError, ValueError) as e:
            raise _corrupt(
                path, label, f"unreadable segment: {e}") from e
        if verify:
            if got_crc != crc:
                raise _corrupt(path, label, "checksum mismatch")
            if arr.size:
                digest = zlib.crc32(arr.reshape(-1).view(np.uint8), digest)
        seg_arrays.append(arr)
    if verify and digest != meta["digest"]:
        raise _corrupt(
            path, "digest", "whole-blob digest mismatch — data region "
            "altered outside any segment")
    return _unpack(packed, seg_arrays)


def _load_v1(f, size, path):
    """Legacy v1 (no checksums): structural bounds validation so a
    truncated file raises a clear corruption error instead of a confusing
    ``struct.error``/``EOFError``."""
    header_len = len(_MAGIC) + 8
    if size < header_len + 8:
        raise _corrupt(
            path, "header", f"file is {size} bytes — truncated below the "
            f"minimum v1 layout ({header_len + 8} bytes)")
    (blob_len,) = struct.unpack("<Q", f.read(8))
    if header_len + blob_len > size - 8:
        raise _corrupt(
            path, "pickle", f"pickle length {blob_len} exceeds file bounds "
            f"(file is {size} bytes) — truncated or corrupt header")
    blob = f.read(blob_len)
    try:
        packed = pickle.loads(blob)
    except Exception as e:
        raise _corrupt(
            path, "pickle", f"undecodable pickle blob: {e}") from e
    f.seek(size - 8)
    (footer_off,) = struct.unpack("<Q", f.read(8))
    if not header_len + blob_len <= footer_off <= size - 8:
        raise _corrupt(
            path, "footer", f"footer offset {footer_off} out of bounds "
            f"(file is {size} bytes) — truncated or corrupt trailer")
    f.seek(footer_off)
    try:
        index = pickle.loads(f.read(size - 8 - footer_off))
    except Exception as e:
        raise _corrupt(
            path, "footer", f"undecodable footer: {e}") from e
    seg_arrays = []
    for i, entry in enumerate(index):
        offset, nbytes, dtype, shape = entry
        if offset + nbytes > footer_off:
            raise _corrupt(
                path, f"segment {i}", f"segment bounds (offset={offset}, "
                f"nbytes={nbytes}) overrun the data region")
        try:
            arr, _ = _read_segment(f, offset, nbytes, dtype, shape,
                                   want_crc=False)   # v1 has no checksums
        except (EOFError, OSError, ValueError) as e:
            raise _corrupt(
                path, f"segment {i}", f"unreadable segment: {e}") from e
        seg_arrays.append(arr)
    return _unpack(packed, seg_arrays)


def _load_legacy(f, size, path):
    # no magic: round-2 plain-pickle — but a v2 file whose header magic
    # was bit-flipped still carries the end marker; report THAT as
    # corruption, not as an unpicklable legacy file
    if size >= len(_END_MAGIC):
        f.seek(size - len(_END_MAGIC))
        if f.read(len(_END_MAGIC)) == _END_MAGIC:
            raise _corrupt(
                path, "header", "magic bytes corrupted (v2 end marker "
                "present but header does not match)")
    f.seek(0)
    try:
        obj = pickle.load(f)
    except Exception as e:
        raise _corrupt(
            path, "header", f"not a paddle_tpu checkpoint and not a "
            f"legacy pickle: {e}") from e
    return _unpack_legacy(obj)


def _unpack_legacy(obj):
    if isinstance(obj, dict):
        if obj.get(_BF16_TAG):
            return Tensor(jnp.asarray(obj["data"]).astype(dtypes.bfloat16))
        return {k: _unpack_legacy(v) for k, v in obj.items()}
    if isinstance(obj, np.ndarray):
        return Tensor(jnp.asarray(obj))
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack_legacy(v) for v in obj)
    return obj
