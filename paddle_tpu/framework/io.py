"""Single-process save/load.

Reference: python/paddle/framework/io.py (save:743 / load:985 — the
reference chunks large pickles to dodge the 4 GB single-bytes limits of
old protocols and pins the pickle protocol). TPU-native format: the
pickled structure stays SMALL — every array above a threshold is replaced
by an indexed placeholder and its bytes are streamed to the same file in
fixed-size chunks after the pickle blob, so a multi-GB state_dict never
materializes a second copy in memory and no pickle frame approaches the
4 GB limits regardless of protocol. bfloat16 arrays round-trip natively
(ml_dtypes numpy dtype).

Layout: ``magic | u64 pickle_len | pickle | raw segments… | footer
pickle | u64 footer_off`` — the footer maps placeholder index ->
(offset, nbytes, dtype, shape). Legacy plain-pickle files (round-2
checkpoints) still load.
"""
from __future__ import annotations

import os
import pickle
import struct

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor

_BF16_TAG = "__bf16__"
_EXT_TAG = "__ext_seg__"
_MAGIC = b"PTCKPT01"
_SEG_THRESHOLD = 1 << 20        # arrays >= 1 MB stream as raw segments
_CHUNK = 64 << 20               # 64 MB write/read granularity


def _to_numpy(arr) -> np.ndarray:
    return np.asarray(arr)


def _pack(obj, segments):
    if isinstance(obj, Tensor):
        obj = obj._data
        # fall through: payloads serialize as arrays, tagged for rehydrate
        arr = _to_numpy(obj)
        if arr.nbytes >= _SEG_THRESHOLD:
            segments.append(arr)
            return {_EXT_TAG: len(segments) - 1, "tensor": True}
        return {"__tensor__": True, "data": arr}
    if isinstance(obj, (jnp.ndarray, np.ndarray)) and not np.isscalar(obj):
        arr = _to_numpy(obj)
        if arr.nbytes >= _SEG_THRESHOLD:
            segments.append(arr)
            return {_EXT_TAG: len(segments) - 1, "tensor": False}
        return arr
    if isinstance(obj, dict):
        return {k: _pack(v, segments) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v, segments) for v in obj)
    return obj


def _rehydrate_array(arr: np.ndarray, as_tensor: bool):
    # every loaded array rehydrates as Tensor regardless of segment size —
    # the load contract must not depend on the save-side threshold
    del as_tensor
    return Tensor(jnp.asarray(arr))


def _unpack(obj, seg_arrays):
    if isinstance(obj, dict):
        if _EXT_TAG in obj:
            return _rehydrate_array(seg_arrays[obj[_EXT_TAG]],
                                    obj.get("tensor", True))
        if obj.get(_BF16_TAG):  # legacy round-2 bf16 encoding
            return Tensor(jnp.asarray(obj["data"]).astype(dtypes.bfloat16))
        if obj.get("__tensor__"):
            return Tensor(jnp.asarray(obj["data"]))
        return {k: _unpack(v, seg_arrays) for k, v in obj.items()}
    if isinstance(obj, np.ndarray):
        return Tensor(jnp.asarray(obj))
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, seg_arrays) for v in obj)
    return obj


def _write_segment(f, arr: np.ndarray) -> tuple:
    offset = f.tell()
    view = memoryview(np.ascontiguousarray(arr).reshape(-1).view(np.uint8))
    for pos in range(0, len(view), _CHUNK):
        f.write(view[pos:pos + _CHUNK])
    return (offset, arr.nbytes, str(arr.dtype), tuple(arr.shape))


def _read_segment(f, offset, nbytes, dtype, shape) -> np.ndarray:
    out = np.empty(int(np.prod(shape)) if shape else 1, np.dtype(dtype))
    buf = out.view(np.uint8).reshape(-1)
    f.seek(offset)
    pos = 0
    while pos < nbytes:
        n = f.readinto(memoryview(buf)[pos:pos + _CHUNK])
        if not n:
            raise EOFError(f"truncated checkpoint segment at {offset}")
        pos += n
    return out.reshape(shape)


def save(obj, path, protocol=4, **configs):
    """Persist ``obj`` (state_dict / nested containers / Tensors).

    ``protocol`` is pinned to the 2..5 range (reference io.py contract);
    large arrays bypass pickle entirely, so any allowed protocol handles
    arbitrarily large checkpoints.
    """
    if not 2 <= int(protocol) <= pickle.HIGHEST_PROTOCOL:
        raise ValueError(
            f"pickle protocol must be in [2, {pickle.HIGHEST_PROTOCOL}], "
            f"got {protocol}")
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    segments = []
    packed = _pack(obj, segments)
    blob = pickle.dumps(packed, protocol=int(protocol))
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", len(blob)))
        f.write(blob)
        index = [_write_segment(f, arr) for arr in segments]
        footer = pickle.dumps(index, protocol=int(protocol))
        footer_off = f.tell()
        f.write(footer)
        f.write(struct.pack("<Q", footer_off))


def load(path, **configs):
    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            # legacy round-2 format: one plain pickle
            f.seek(0)
            return _unpack_legacy(pickle.load(f))
        (blob_len,) = struct.unpack("<Q", f.read(8))
        packed = pickle.loads(f.read(blob_len))
        f.seek(-8, os.SEEK_END)
        (footer_off,) = struct.unpack("<Q", f.read(8))
        f.seek(footer_off)
        end = f.seek(0, os.SEEK_END) - 8
        f.seek(footer_off)
        index = pickle.loads(f.read(end - footer_off))
        seg_arrays = [_read_segment(f, *entry) for entry in index]
        return _unpack(packed, seg_arrays)


def _unpack_legacy(obj):
    if isinstance(obj, dict):
        if obj.get(_BF16_TAG):
            return Tensor(jnp.asarray(obj["data"]).astype(dtypes.bfloat16))
        return {k: _unpack_legacy(v) for k, v in obj.items()}
    if isinstance(obj, np.ndarray):
        return Tensor(jnp.asarray(obj))
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack_legacy(v) for v in obj)
    return obj
