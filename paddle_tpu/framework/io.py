"""Single-process save/load.

Reference: python/paddle/framework/io.py (save:743 / load:985 — pickled
nested state_dict, protocol 4). Tensors are serialized as numpy arrays and
rehydrated onto the current device on load; bfloat16 round-trips through a
uint16 view since numpy lacks the dtype.
"""
from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor

_BF16_TAG = "__bf16__"


def _pack(obj):
    if isinstance(obj, Tensor):
        arr = obj._data
        if np.dtype(arr.dtype) == dtypes.bfloat16:
            return {_BF16_TAG: True,
                    "data": np.asarray(arr.astype(jnp.float32))}
        return np.asarray(arr)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj):
    if isinstance(obj, dict):
        if obj.get(_BF16_TAG):
            return Tensor(jnp.asarray(obj["data"]).astype(dtypes.bfloat16))
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, np.ndarray):
        return Tensor(jnp.asarray(obj))
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return _unpack(pickle.load(f))
