"""Device RNG state helpers (reference: python/paddle/framework/random.py).
On TPU there is one counter-based stream; the "cuda" names are aliases."""
from ..core.generator import get_rng_state, set_rng_state


def get_cuda_rng_state():
    return get_rng_state()


def set_cuda_rng_state(state):
    return set_rng_state(state)
