"""paddle_tpu.framework — misc framework-level API
(reference: python/paddle/framework/__init__.py)."""
from ..core.dispatch import grad_enabled
from ..core.generator import get_rng_state, seed, set_rng_state
from .io import load, save
from .random import get_cuda_rng_state, set_cuda_rng_state


def in_dynamic_mode():
    from ..jit.api import in_capture_mode
    return not in_capture_mode()


def in_pir_mode():
    return False


def use_pir_api():
    return False
from .tensor_types import SelectedRows, TensorArray
from ..core.string_tensor import StringTensor, to_string_tensor
