"""paddle.metric — streaming metrics (reference: python/paddle/metric/
metrics.py — Metric base, Accuracy:181, Precision:310, Recall:408,
Auc:481). Host-side numpy accumulation over device-computed correctness
tensors, matching the reference's compute/update/accumulate split."""
from __future__ import annotations

import abc

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return np.asarray(x.numpy() if isinstance(x, Tensor) else x)


class Metric(abc.ABC):
    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def name(self):
        ...

    def compute(self, *args):
        """Device-side pre-computation; default passthrough."""
        return args if len(args) > 1 else args[0]


class Accuracy(Metric):
    """Top-k accuracy (reference metrics.py Accuracy:181)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            if label_np.shape[-1] == pred_np.shape[-1]:
                # one-hot / soft label
                label_np = np.argmax(label_np, axis=-1)
            else:
                # conventional [B, 1] class-index column (reference
                # Accuracy treats this as indices, not one-hot)
                label_np = label_np[..., 0]
        correct = (idx == label_np[..., None]).astype(np.float32)
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        accs = []
        for k in self.topk:
            num = correct[..., :k].sum()
            accs.append(num / max(correct.shape[0], 1))
            self.total[self.topk.index(k)] += num
            self.count[self.topk.index(k)] += correct.shape[0]
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    """Binary precision (reference metrics.py Precision:310)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels)
        pred_pos = (preds.reshape(-1) > 0.5)
        lab = labels.reshape(-1).astype(bool)
        self.tp += int(np.sum(pred_pos & lab))
        self.fp += int(np.sum(pred_pos & ~lab))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (reference metrics.py Recall:408)."""

    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels)
        pred_pos = (preds.reshape(-1) > 0.5)
        lab = labels.reshape(-1).astype(bool)
        self.tp += int(np.sum(pred_pos & lab))
        self.fn += int(np.sum(~pred_pos & lab))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via thresholded confusion bins (reference metrics.py
    Auc:481, the '_stat' histogram approach)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = labels.reshape(-1)
        bins = np.clip((preds * self.num_thresholds).astype(int), 0,
                       self.num_thresholds)
        pos = labels.astype(bool)
        self._stat_pos += np.bincount(bins[pos],
                                      minlength=self.num_thresholds + 1)
        self._stat_neg += np.bincount(bins[~pos],
                                      minlength=self.num_thresholds + 1)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # integrate TPR over FPR from the highest threshold down,
        # anchored at the (0, 0) origin so saturated/degenerate score
        # distributions still integrate the full curve
        pos = np.concatenate([[0], self._stat_pos[::-1].cumsum()])
        neg = np.concatenate([[0], self._stat_neg[::-1].cumsum()])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Chunk-level precision/recall/F1 for sequence labeling (NER).

    Tag encoding follows the reference (phi/kernels/cpu/chunk_eval... via
    python/paddle/static/nn/metric.py chunk_eval): for scheme IOB the tag of
    chunk type t is ``t * tag_num + pos`` with pos in {B=0, I=1}; IOE uses
    {I=0, E=1}; IOBES uses {B, I, E, S}; "plain" has one tag per type.
    Returns (precision, recall, f1, num_infer, num_label, num_correct)
    as float/int64 Tensors.
    """
    import jax.numpy as jnp
    import numpy as np
    from ..core.tensor import Tensor

    schemes = {"IOB": ["B", "I"], "IOE": ["I", "E"],
               "IOBES": ["B", "I", "E", "S"], "plain": ["U"]}
    if chunk_scheme not in schemes:
        raise ValueError(f"unknown chunk_scheme {chunk_scheme}")
    tags = schemes[chunk_scheme]
    tag_num = len(tags)
    excluded = set(excluded_chunk_types or ())

    def decode(seq):
        """token tags -> set of (start, end, type) chunks"""
        chunks = []
        start = None
        cur_type = None
        for i, t in enumerate(list(seq) + [-1]):
            if t < 0 or t >= num_chunk_types * tag_num:
                pos, typ = None, None
            else:
                typ, pos = divmod(int(t), tag_num)
                pos = tags[pos]
            if chunk_scheme == "plain":
                if typ is not None:
                    if cur_type == typ:
                        pass  # continues
                    else:
                        if start is not None:
                            chunks.append((start, i - 1, cur_type))
                        start, cur_type = i, typ
                else:
                    if start is not None:
                        chunks.append((start, i - 1, cur_type))
                    start = cur_type = None
                continue
            begin = pos in ("B", "S") or (chunk_scheme == "IOE" and pos == "I"
                                          and cur_type != typ)
            inside = pos in ("I",) and cur_type == typ and start is not None
            if chunk_scheme == "IOB":
                if pos == "B" or (pos == "I" and not inside):
                    if start is not None:
                        chunks.append((start, i - 1, cur_type))
                    start, cur_type = i, typ
                elif pos == "I":
                    pass
                else:
                    if start is not None:
                        chunks.append((start, i - 1, cur_type))
                    start = cur_type = None
            elif chunk_scheme == "IOE":
                if start is None or cur_type != typ:
                    if start is not None:
                        chunks.append((start, i - 1, cur_type))
                    start, cur_type = (i, typ) if typ is not None else (None, None)
                if pos == "E" and start is not None:
                    chunks.append((start, i, cur_type))
                    start = cur_type = None
            else:  # IOBES
                if pos == "S":
                    if start is not None:
                        chunks.append((start, i - 1, cur_type))
                    chunks.append((i, i, typ))
                    start = cur_type = None
                elif pos == "B":
                    if start is not None:
                        chunks.append((start, i - 1, cur_type))
                    start, cur_type = i, typ
                elif pos == "I" and cur_type == typ and start is not None:
                    pass
                elif pos == "E" and cur_type == typ and start is not None:
                    chunks.append((start, i, cur_type))
                    start = cur_type = None
                else:
                    if start is not None:
                        chunks.append((start, i - 1, cur_type))
                    start = cur_type = None
        return {c for c in chunks if c[2] not in excluded}

    inp = _np(input)
    lab = _np(label)
    if inp.ndim == 1:
        inp, lab = inp[None], lab[None]
    sl = (_np(seq_length).ravel() if seq_length is not None
          else np.full(inp.shape[0], inp.shape[1], np.int64))
    n_inf = n_lab = n_cor = 0
    for b in range(inp.shape[0]):
        ic = decode(inp[b, :sl[b]])
        lc = decode(lab[b, :sl[b]])
        n_inf += len(ic)
        n_lab += len(lc)
        n_cor += len(ic & lc)
    prec = n_cor / n_inf if n_inf else 0.0
    rec = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
    mk = lambda v, dt: Tensor(jnp.asarray(np.asarray([v], dtype=dt)))
    return (mk(prec, np.float32), mk(rec, np.float32), mk(f1, np.float32),
            mk(n_inf, np.int64), mk(n_lab, np.int64), mk(n_cor, np.int64))


__all__ += ["chunk_eval"]


class DetectionMAP:
    """VOC mean-average-precision over detection results (reference
    detection_map op, phi/kernels/.../detection_map_op; python/paddle
    fluid metrics.DetectionMAP).

    update() takes per-image detections (M, 6) [label, score, x1, y1, x2,
    y2] and ground truths (G, 5) [label, x1, y1, x2, y2] (+ optional
    difficult flags); accumulate() returns mAP under 'integral' or
    '11point' AP.
    """

    def __init__(self, class_num, overlap_threshold=0.5,
                 evaluate_difficult=False, ap_version="integral"):
        if ap_version not in ("integral", "11point"):
            raise ValueError(f"unknown ap_version {ap_version}")
        self.class_num = class_num
        self.thr = overlap_threshold
        self.eval_difficult = evaluate_difficult
        self.ap_version = ap_version
        self.reset()

    def reset(self):
        import collections
        self._scores = collections.defaultdict(list)  # cls -> [(score, tp)]
        self._npos = collections.defaultdict(int)

    @staticmethod
    def _iou(a, b):
        import numpy as np
        ix1 = np.maximum(a[0], b[0]); iy1 = np.maximum(a[1], b[1])
        ix2 = np.minimum(a[2], b[2]); iy2 = np.minimum(a[3], b[3])
        inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def update(self, detections, gt, difficult=None):
        import numpy as np
        det = _np(detections)
        gtn = _np(gt)
        diff = (_np(difficult).ravel().astype(bool)
                if difficult is not None
                else np.zeros(gtn.shape[0], bool))
        for c in range(self.class_num):
            gidx = np.nonzero(gtn[:, 0].astype(int) == c)[0]
            if not self.eval_difficult:
                self._npos[c] += int((~diff[gidx]).sum())
            else:
                self._npos[c] += len(gidx)
            dets_c = det[det[:, 0].astype(int) == c]
            order = np.argsort(-dets_c[:, 1])
            matched = set()
            for di in order:
                drow = dets_c[di]
                best, best_g = 0.0, -1
                for g in gidx:
                    ov = self._iou(drow[2:6], gtn[g, 1:5])
                    if ov > best:
                        best, best_g = ov, g
                if best >= self.thr and best_g not in matched:
                    if diff[best_g] and not self.eval_difficult:
                        continue  # difficult gt: ignore the detection
                    matched.add(best_g)
                    self._scores[c].append((float(drow[1]), 1))
                else:
                    self._scores[c].append((float(drow[1]), 0))

    def accumulate(self):
        import numpy as np
        aps = []
        for c in range(self.class_num):
            npos = self._npos[c]
            if npos == 0 and not self._scores[c]:
                continue
            if not self._scores[c]:
                aps.append(0.0)
                continue
            rows = sorted(self._scores[c], key=lambda r: -r[0])
            tp = np.cumsum([r[1] for r in rows])
            fp = np.cumsum([1 - r[1] for r in rows])
            rec = tp / max(npos, 1)
            prec = tp / np.maximum(tp + fp, 1e-12)
            if self.ap_version == "11point":
                ap = 0.0
                for t in np.linspace(0, 1, 11):
                    p = prec[rec >= t].max() if (rec >= t).any() else 0.0
                    ap += p / 11
            else:
                mrec = np.concatenate([[0], rec, [1]])
                mpre = np.concatenate([[0], prec, [0]])
                for i in range(mpre.size - 2, -1, -1):
                    mpre[i] = max(mpre[i], mpre[i + 1])
                idx = np.nonzero(mrec[1:] != mrec[:-1])[0]
                ap = float(((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]).sum())
            aps.append(float(ap))
        return float(np.mean(aps)) if aps else 0.0


__all__ += ["DetectionMAP"]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Top-k accuracy as a scalar tensor (reference metric/metrics.py
    accuracy :763): correct if the true label appears in the top-k
    predictions."""
    import jax
    import jax.numpy as jnp

    from ..core import dispatch
    from ..core.tensor import Tensor, as_tensor

    it = input if isinstance(input, Tensor) else as_tensor(input)
    lt = label if isinstance(label, Tensor) else as_tensor(label)

    def f(a, y):
        _, topk = jax.lax.top_k(a, k)
        y = y.reshape(-1, 1).astype(topk.dtype)
        hit = (topk == y).any(axis=1)
        return hit.astype(jnp.float32).mean()
    return dispatch.call("metric_accuracy", f, [it, lt],
                         differentiable_mask=[False, False])


__all__ += ["accuracy"]
