"""paddle.metric — streaming metrics (reference: python/paddle/metric/
metrics.py — Metric base, Accuracy:181, Precision:310, Recall:408,
Auc:481). Host-side numpy accumulation over device-computed correctness
tensors, matching the reference's compute/update/accumulate split."""
from __future__ import annotations

import abc

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return np.asarray(x.numpy() if isinstance(x, Tensor) else x)


class Metric(abc.ABC):
    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def name(self):
        ...

    def compute(self, *args):
        """Device-side pre-computation; default passthrough."""
        return args if len(args) > 1 else args[0]


class Accuracy(Metric):
    """Top-k accuracy (reference metrics.py Accuracy:181)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            if label_np.shape[-1] == pred_np.shape[-1]:
                # one-hot / soft label
                label_np = np.argmax(label_np, axis=-1)
            else:
                # conventional [B, 1] class-index column (reference
                # Accuracy treats this as indices, not one-hot)
                label_np = label_np[..., 0]
        correct = (idx == label_np[..., None]).astype(np.float32)
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        accs = []
        for k in self.topk:
            num = correct[..., :k].sum()
            accs.append(num / max(correct.shape[0], 1))
            self.total[self.topk.index(k)] += num
            self.count[self.topk.index(k)] += correct.shape[0]
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    """Binary precision (reference metrics.py Precision:310)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels)
        pred_pos = (preds.reshape(-1) > 0.5)
        lab = labels.reshape(-1).astype(bool)
        self.tp += int(np.sum(pred_pos & lab))
        self.fp += int(np.sum(pred_pos & ~lab))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (reference metrics.py Recall:408)."""

    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels)
        pred_pos = (preds.reshape(-1) > 0.5)
        lab = labels.reshape(-1).astype(bool)
        self.tp += int(np.sum(pred_pos & lab))
        self.fn += int(np.sum(~pred_pos & lab))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via thresholded confusion bins (reference metrics.py
    Auc:481, the '_stat' histogram approach)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = labels.reshape(-1)
        bins = np.clip((preds * self.num_thresholds).astype(int), 0,
                       self.num_thresholds)
        pos = labels.astype(bool)
        self._stat_pos += np.bincount(bins[pos],
                                      minlength=self.num_thresholds + 1)
        self._stat_neg += np.bincount(bins[~pos],
                                      minlength=self.num_thresholds + 1)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # integrate TPR over FPR from the highest threshold down,
        # anchored at the (0, 0) origin so saturated/degenerate score
        # distributions still integrate the full curve
        pos = np.concatenate([[0], self._stat_pos[::-1].cumsum()])
        neg = np.concatenate([[0], self._stat_neg[::-1].cumsum()])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]
