"""Higher-order functional autograd: jacobian / hessian / jvp / vjp.

Reference contracts: ``python/paddle/autograd/autograd.py`` (``jacobian``
:450 / ``hessian`` :544 over computed ``ys``/``xs`` with ``batch_axis``,
returning lazily-evaluated ``Jacobian``/``Hessian`` views) and
``python/paddle/incubate/autograd/functional.py`` (``vjp`` :22, ``jvp``
:80 — forward-mode built from double reverse, the
``_double_backward_trick`` :143).

TPU-native notes: rows are produced by replaying the recorded tape
(``paddle.grad`` with ``retain_graph``), so the same object works for any
eager computation; materialized blocks are cached per row. The jvp uses
the reference's double-backward construction, which our engine supports
natively (``_vjp_on_tape``), keeping the whole thing one reverse engine
instead of a separate forward-mode trace.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

from ..core.tensor import Tensor

__all__ = ["jacobian", "hessian", "Jacobian", "Hessian", "vjp", "jvp"]


def _as_tensors(xs):
    return (xs,) if isinstance(xs, Tensor) else tuple(xs)


def _flat_nonbatch(t: Tensor, batch_axis: Optional[int]):
    """(B?, N) view of t with batch axis (if any) moved to front."""
    from .. import ops
    if batch_axis is None:
        return ops.reshape(t, [-1])
    if batch_axis != 0:
        raise ValueError(
            f"batch_axis must be None or 0 (reference contract), got "
            f"{batch_axis}")
    return ops.reshape(t, [t.shape[0], -1])


class Jacobian:
    """Lazy d(ys)/d(xs) for ONE (ys, xs) pair.

    Shape: (M, N) without batch, (B, M, N) with ``batch_axis=0`` where
    M/N are the flattened non-batch sizes of ys/xs. Rows are computed on
    first access and cached; ``[:]`` materializes everything.
    """

    def __init__(self, ys: Tensor, xs: Tensor,
                 batch_axis: Optional[int] = None, _create_graph=False):
        self._ys = ys
        self._xs = xs
        self._batch_axis = batch_axis
        self._create_graph = _create_graph
        self._yflat = _flat_nonbatch(ys, batch_axis)
        m = self._yflat.shape[-1]
        if batch_axis is None:
            n = int(np.prod(xs.shape)) if xs.shape else 1
            self.shape = (m, n)
        else:
            b = xs.shape[0]
            n = int(np.prod(xs.shape[1:])) if xs.shape[1:] else 1
            self.shape = (b, m, n)
        self._rows = {}

    def _row(self, i: int) -> Tensor:
        """d yflat[..., i] / d xs, flattened like xs (batch leading)."""
        if i not in self._rows:
            from .. import ops
            from . import grad as pgrad
            if self._batch_axis is None:
                y_i = self._yflat[i]
            else:
                y_i = self._yflat[:, i].sum()  # batch rows are independent
            (g,) = pgrad(y_i, [self._xs], retain_graph=True,
                         create_graph=self._create_graph,
                         allow_unused=True)
            if g is None:
                g = ops.zeros_like(self._xs)
            self._rows[i] = _flat_nonbatch(g, self._batch_axis)
        return self._rows[i]

    def _materialize(self) -> Tensor:
        from .. import ops
        m = self.shape[0] if self._batch_axis is None else self.shape[1]
        rows = [self._row(i) for i in range(m)]
        stacked = ops.stack(rows, axis=0 if self._batch_axis is None else 1)
        return stacked

    def __getitem__(self, idx):
        # single-row access stays O(1 backward pass) in the unbatched
        # case (the first axis IS the row axis there); everything else
        # materializes
        if isinstance(idx, int) and self._batch_axis is None:
            return self._row(idx)
        full = self._materialize()
        return full[idx]

    def __array__(self, dtype=None):
        arr = np.asarray(self._materialize().numpy())
        return arr.astype(dtype) if dtype is not None else arr

    def numpy(self):
        return self.__array__()

    def __repr__(self):
        return f"Jacobian(shape={self.shape})"


class Hessian(Jacobian):
    """d²(ys)/d(xs)² for scalar (or per-batch scalar) ``ys``: the
    Jacobian of the create_graph gradient."""

    def __init__(self, ys: Tensor, xs: Tensor,
                 batch_axis: Optional[int] = None):
        from . import grad as pgrad
        if batch_axis is None:
            scalar = ys.sum() if ys.shape else ys
        else:
            scalar = ys.sum()
        (g,) = pgrad(scalar, [xs], create_graph=True, retain_graph=True)
        super().__init__(g, xs, batch_axis)


def _nest(ys, xs, batch_axis, cls):
    ys_t = _as_tensors(ys)
    xs_t = _as_tensors(xs)
    rows = [tuple(cls(y, x, batch_axis) for x in xs_t) for y in ys_t]
    # reference nesting: single/one-level/two-level mirroring input nests
    if isinstance(ys, Tensor) and isinstance(xs, Tensor):
        return rows[0][0]
    if isinstance(ys, Tensor):
        return rows[0]
    if isinstance(xs, Tensor):
        return tuple(r[0] for r in rows)
    return tuple(rows)


def jacobian(ys, xs, batch_axis: Optional[int] = None):
    """paddle.autograd.jacobian (reference autograd.py:450)."""
    return _nest(ys, xs, batch_axis, Jacobian)


def hessian(ys, xs, batch_axis: Optional[int] = None):
    """paddle.autograd.hessian (reference autograd.py:544). ``ys`` must
    be scalar (or shape [B] with ``batch_axis=0``). A tuple ``xs``
    returns the reference's tuple-of-tuples: ``H[i][j]`` is the
    d²ys/∂xs[i]∂xs[j] block (cross-partials included)."""
    if isinstance(ys, (tuple, list)):
        raise ValueError("hessian expects a single (scalar) ys tensor")
    nb = ys.shape if batch_axis is None else ys.shape[1:]
    if int(np.prod(nb)) != 1:
        raise ValueError(
            f"hessian needs scalar ys (per batch), got shape {ys.shape}")
    if isinstance(xs, Tensor):
        return Hessian(ys, xs, batch_axis)
    from . import grad as pgrad
    xs_t = _as_tensors(xs)
    scalar = ys.sum() if ys.shape else ys
    firsts = pgrad(scalar, list(xs_t), create_graph=True,
                   retain_graph=True)
    return tuple(
        tuple(Jacobian(g_i, x_j, batch_axis) for x_j in xs_t)
        for g_i in firsts)


# ------------------------------------------------------- functional pair
def vjp(func, xs, v=None):
    """(ys, vjp_result): reverse-mode product (reference
    incubate/autograd/functional.py:22). Inputs unused by ``func`` get
    zero cotangents; callers' ``stop_gradient`` flags are restored."""
    from . import grad as pgrad
    from .. import ops
    xs_t = _as_tensors(xs)
    saved = [x.stop_gradient for x in xs_t]
    try:
        for x in xs_t:
            x.stop_gradient = False
        ys = func(*xs_t)
        ys_t = _as_tensors(ys)
        if v is None:
            v_t = [ops.ones_like(y) for y in ys_t]
        else:
            v_t = list(_as_tensors(v))
        grads = pgrad(list(ys_t), list(xs_t), grad_outputs=v_t,
                      retain_graph=True, allow_unused=True)
        grads = [g if g is not None else ops.zeros_like(x)
                 for g, x in zip(grads, xs_t)]
    finally:
        for x, s in zip(xs_t, saved):
            x.stop_gradient = s
    out = grads[0] if isinstance(xs, Tensor) else tuple(grads)
    return ys, out


def jvp(func, xs, v=None):
    """(ys, jvp_result): forward-mode product via the double-backward
    trick (reference functional.py:80/:143 — jvp = ∂/∂u [vjp(u)·v] where
    u is a zero cotangent with grad enabled)."""
    from . import grad as pgrad
    from .. import ops
    xs_t = _as_tensors(xs)
    saved = [x.stop_gradient for x in xs_t]
    try:
        for x in xs_t:
            x.stop_gradient = False
        ys = func(*xs_t)
        ys_t = _as_tensors(ys)
        if v is None:
            v_t = [ops.ones_like(x) for x in xs_t]
        else:
            v_t = list(_as_tensors(v))
        # u: zero cotangents, differentiable (reference
        # _zeros_like_with_grad)
        u = []
        for y in ys_t:
            z = ops.zeros_like(y)
            z.stop_gradient = False
            u.append(z)
        first = pgrad(list(ys_t), list(xs_t), grad_outputs=u,
                      create_graph=True, retain_graph=True,
                      allow_unused=True)
        first = [f if f is not None else ops.zeros_like(x)
                 for f, x in zip(first, xs_t)]
        second = pgrad(first, u, grad_outputs=v_t, retain_graph=True,
                       allow_unused=True)
        second = [s if s is not None else ops.zeros_like(y)
                  for s, y in zip(second, ys_t)]
    finally:
        for x, s in zip(xs_t, saved):
            x.stop_gradient = s
    out = second[0] if isinstance(ys, Tensor) else tuple(second)
    return ys, out
