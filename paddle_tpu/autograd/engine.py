"""Autograd graph + backward engine.

Capability parity with the reference's eager autograd (reference:
paddle/fluid/eager/grad_node_info.h:197 GradNodeBase, backward.cc:105
RunBackward, accumulation/accumulation_node.h). TPU-native design: instead of
per-op hand-written GradNode classes, each forward op records ONE GradNode
holding the jax.vjp closure of its lowering — the VJP is computed by jax's
partial-eval machinery, runs on-device, and is itself jax-traceable (which is
what makes create_graph / double backward and whole-graph capture work).
"""
from __future__ import annotations

from collections import defaultdict, deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

FLOAT0 = jax.dtypes.float0


class GradNode:
    """One recorded op. Edges point input-wards (to producer nodes)."""

    __slots__ = ("name", "vjp_fn", "edges", "out_avals", "in_requires",
                 "output_hooks", "retain_outputs", "out_tuple",
                 "primal_fn", "saved_inputs")

    def __init__(self, name: str, vjp_fn, edges, out_avals, in_requires,
                 out_tuple: bool = False, primal_fn=None, saved_inputs=None):
        self.name = name
        self.vjp_fn = vjp_fn
        # For create_graph (double backward): re-derive the VJP as a function
        # of (primals, cotangents) so grads-of-grads see the primal deps
        # (reference TensorWrapper saved-tensor role, eager/tensor_wrapper.h:39).
        self.primal_fn = primal_fn
        self.saved_inputs = saved_inputs
        # edges[i] = (producer GradNode | AccumulationNode | None, output_index)
        self.edges: List[Tuple[Optional["GradNode"], int]] = edges
        self.out_avals = out_avals        # [(shape, dtype)] per output
        self.in_requires = in_requires    # [bool] per input: route grad?
        self.out_tuple = out_tuple        # primal fn returned a tuple
        self.output_hooks: Dict[int, list] = {}
        self.retain_outputs: Dict[int, Tensor] = {}

    def num_outputs(self):
        return len(self.out_avals)

    def __repr__(self):
        return f"GradNode({self.name})"


class AccumulationNode:
    """Leaf sink: accumulates into ``tensor.grad`` (reference GradNodeAccumulation)."""

    __slots__ = ("tensor_ref",)

    def __init__(self, tensor: Tensor):
        self.tensor_ref = tensor

    def num_outputs(self):
        return 1

    def __repr__(self):
        return f"AccumulationNode({self.tensor_ref.name})"


def _zero_cotangent(shape, dtype):
    d = np.dtype(dtype)
    if not (np.issubdtype(d, np.inexact) or d == jnp.bfloat16.dtype):
        return np.zeros(shape, dtype=FLOAT0)
    return jnp.zeros(shape, dtype=d)


def _is_float0(x):
    if isinstance(x, Tensor):
        x = x._data
    return getattr(x, "dtype", None) == FLOAT0


def _accumulate(a, b):
    """Sum two cotangents. Either may be a raw array (fast path) or a taped
    Tensor (create_graph path) — Tensor addition goes through the dispatcher
    so the accumulation itself is recorded."""
    if a is None:
        return b
    if b is None:
        return a
    if isinstance(a, Tensor) or isinstance(b, Tensor):
        from ..core import dispatch
        ta = a if isinstance(a, Tensor) else Tensor(a)
        tb = b if isinstance(b, Tensor) else Tensor(b)
        return dispatch.call("grad_add", lambda x, y: x + y, [ta, tb], {})
    return a + b


def _raw(x):
    return x._data if isinstance(x, Tensor) else x


def _collect_reachable(roots: Sequence[GradNode], stop_nodes=frozenset()):
    """DFS input-wards; count consumer edges per node (dependency counts)."""
    deps: Dict[int, int] = defaultdict(int)
    nodes: Dict[int, object] = {}
    stack = list(roots)
    seen = set()
    for r in roots:
        nodes[id(r)] = r
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        if isinstance(node, AccumulationNode) or id(node) in stop_nodes:
            continue
        for producer, _ in node.edges:
            if producer is None:
                continue
            deps[id(producer)] += 1
            nodes[id(producer)] = producer
            if id(producer) not in seen:
                stack.append(producer)
    return deps, nodes


def run_backward(tensors: Sequence[Tensor], grad_tensors=None,
                 retain_graph: bool = False, create_graph: bool = False,
                 inputs: Optional[Sequence[Tensor]] = None,
                 accumulate_into_leaves: bool = True):
    """Reverse-topological execution (reference eager/backward.cc RunBackward).

    When ``inputs`` is given, returns grads for exactly those tensors (the
    ``paddle.grad`` path); otherwise accumulates into leaf ``.grad`` fields.
    """
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)

    # Seed cotangent buffers: buffers[id(node)][out_idx] -> cotangent array
    buffers: Dict[int, Dict[int, object]] = defaultdict(dict)
    roots: List[GradNode] = []
    leaf_seeds: List[Tuple[Tensor, object]] = []

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise RuntimeError(
                f"Tensor {t.name} has stop_gradient=True; backward needs a grad-tracked output")
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    f"grad must be provided for non-scalar output {t.name} (shape {t.shape})")
            g_arr = jnp.ones(t._data.shape, dtype=t._data.dtype)
        elif isinstance(g, Tensor):
            g_arr = g if create_graph else g._data
        else:
            g_arr = jnp.asarray(g, dtype=t._data.dtype)
        node = t.grad_node
        if node is None:
            leaf_seeds.append((t, g_arr))
            continue
        buffers[id(node)][t.output_index] = _accumulate(
            buffers[id(node)].get(t.output_index), g_arr)
        roots.append(node)

    # Watch set for the paddle.grad path.
    input_grads: Optional[Dict[int, object]] = None
    watched: Dict[int, List[Tuple[int, Tensor]]] = defaultdict(list)  # node id -> [(out_idx, tensor)]
    watched_leaves: Dict[int, Tensor] = {}
    if inputs is not None:
        input_grads = {}
        for i, t in enumerate(inputs):
            if t.grad_node is not None:
                watched[id(t.grad_node)].append((t.output_index, t))
            else:
                watched_leaves[id(t)] = t
            input_grads[id(t)] = None

    deps, node_map = _collect_reachable(roots)

    ready = deque()
    pending = dict(deps)
    for r in set(id(n) for n in roots):
        if pending.get(r, 0) == 0:
            ready.append(node_map[r])
    queued = set(id(n) for n in ready)

    executed = []

    def finalize_output_grad(node, out_idx, grad):
        """Apply hooks registered on the tensor at (node, out_idx)."""
        for hook in node.output_hooks.get(out_idx, ()):
            res = hook(grad if isinstance(grad, Tensor) else Tensor(grad))
            if res is not None:
                grad = res
        if out_idx in node.retain_outputs:
            t = node.retain_outputs[out_idx]
            prev = t._grad if t._grad is not None else None
            acc = _accumulate(prev, grad)
            t._grad = acc if isinstance(acc, Tensor) else Tensor(acc)
        return grad

    while ready:
        node = ready.popleft()
        executed.append(node)
        buf = buffers.pop(id(node), {})

        # Assemble full cotangent tuple for this node's outputs.
        cts = []
        for i, (shape, dt) in enumerate(node.out_avals):
            g = buf.get(i)
            if g is not None:
                g = finalize_output_grad(node, i, g)
            cts.append(g if g is not None else _zero_cotangent(shape, dt))
        if input_grads is not None and id(node) in watched:
            for out_idx, t in watched[id(node)]:
                g = cts[out_idx]
                input_grads[id(t)] = None if _is_float0(g) else _accumulate(
                    input_grads.get(id(t)), g)

        if node.vjp_fn is None:
            raise RuntimeError(
                f"Trying to backward through {node.name} a second time; "
                "set retain_graph=True if you need to")
        if create_graph:
            in_grads = _vjp_on_tape(node, cts)
        else:
            raw_cts = [_raw(c) for c in cts]
            in_grads = node.vjp_fn(tuple(raw_cts) if node.out_tuple else raw_cts[0])
        if not retain_graph and not create_graph:
            node.vjp_fn = None

        for (producer, out_idx), g, req in zip(node.edges, in_grads, node.in_requires):
            if producer is None or not req or g is None or _is_float0(g):
                continue
            if isinstance(producer, AccumulationNode):
                _leaf_accumulate(producer.tensor_ref, g, input_grads,
                                 watched_leaves, accumulate_into_leaves)
                continue
            pbuf = buffers[id(producer)]
            pbuf[out_idx] = _accumulate(pbuf.get(out_idx), g)
            pending[id(producer)] -= 1
            if pending[id(producer)] == 0 and id(producer) not in queued:
                queued.add(id(producer))
                ready.append(producer)

    # Nodes never reached ready because some consumers were unreachable: flush
    # any with partial deps (can happen when outputs list doesn't cover all uses).
    for nid, cnt in list(pending.items()):
        if cnt > 0 and nid in buffers and nid not in queued:
            pass  # grads through unvisited consumers are structurally zero

    for t, g_arr in leaf_seeds:
        _leaf_accumulate(t, g_arr, input_grads, watched_leaves, accumulate_into_leaves)

    if input_grads is not None:
        out = []
        for t in inputs:
            g = input_grads.get(id(t))
            if g is None:
                out.append(None)
            else:
                out.append(g if isinstance(g, Tensor) else Tensor(g))
        return out
    return None


def _leaf_accumulate(t: Tensor, g, input_grads, watched_leaves, accumulate_into_leaves):
    if _is_float0(g):  # tpulint: disable=TPU105 — taint FP: _is_float0 checks g's DTYPE (jax's zero-tangent sentinel), static metadata — no device read
        return
    for hook in t._backward_hooks:
        res = hook(g if isinstance(g, Tensor) else Tensor(g))
        if res is not None:
            g = res
    if input_grads is not None and id(t) in watched_leaves:
        input_grads[id(t)] = _accumulate(input_grads.get(id(t)), g)
        if not accumulate_into_leaves:
            return
    acc = _accumulate(t._grad, g)
    acc = acc if isinstance(acc, Tensor) else Tensor(acc)
    # ZeRO stage-2/3: a param tagged with a grad sharding stores its grad
    # reduce-scattered over the sharding axis instead of replicated
    # (reference group_sharded_stage2.py:46 grad storage; here the shard
    # placement IS the storage policy and XLA emits the reduce-scatter).
    gs = getattr(t, "_grad_sharding", None)
    if gs is not None:
        if isinstance(acc._data, jax.core.Tracer):
            acc._data = jax.lax.with_sharding_constraint(acc._data, gs)
        else:
            acc._data = jax.device_put(acc._data, gs)
    t._grad = acc


def _vjp_on_tape(node: GradNode, cts):
    """create_graph=True: run the VJP *through the dispatcher*, expressed as a
    function of (primal inputs, cotangents), so the backward computation is
    itself recorded with full primal dependencies (double backward)."""
    from ..core import dispatch

    ct_tensors = [c if isinstance(c, Tensor) else Tensor(c, stop_gradient=True)
                  for c in cts]

    if node.primal_fn is not None and node.saved_inputs is not None:
        n_primal = len(node.saved_inputs)

        def fn(*args):
            primals, ct_arrays = args[:n_primal], args[n_primal:]
            _, vjp = jax.vjp(node.primal_fn, *primals)
            arg = tuple(ct_arrays) if node.out_tuple else ct_arrays[0]
            return tuple(vjp(arg))

        outs = dispatch.call(f"{node.name}_grad", fn,
                             list(node.saved_inputs) + ct_tensors, {},
                             multi_output=True)
    else:
        def fn(*ct_arrays):
            arg = tuple(ct_arrays) if node.out_tuple else ct_arrays[0]
            return tuple(node.vjp_fn(arg))

        outs = dispatch.call(f"{node.name}_grad", fn, ct_tensors, {},
                             multi_output=True)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    return list(outs)
