"""User-facing autograd API (reference: python/paddle/autograd/)."""
from __future__ import annotations

from typing import List, Optional, Sequence

from ..core.dispatch import (enable_grad, no_grad, set_grad_enabled_ctx as
                             set_grad_enabled, grad_enabled)
from ..core.tensor import Tensor
from .engine import AccumulationNode, GradNode, run_backward
from .pylayer import PyLayer, PyLayerContext
from .functional import Hessian, Jacobian, hessian, jacobian


def is_grad_enabled() -> bool:
    return grad_enabled()


def backward(tensors: Sequence[Tensor], grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward (reference python/paddle/autograd/backward_mode.py)."""
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is not None and isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         only_inputs=True, allow_unused=False, no_grad_vars=None):
    """paddle.grad — functional gradients without touching .grad fields
    (reference python/paddle/base/dygraph/base.py grad)."""
    outs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    ins = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    gouts = grad_outputs
    if gouts is not None and isinstance(gouts, Tensor):
        gouts = [gouts]
    if retain_graph is None:
        retain_graph = create_graph
    grads = run_backward(outs, gouts, retain_graph=retain_graph,
                         create_graph=create_graph, inputs=ins,
                         accumulate_into_leaves=False)
    if not allow_unused:
        for t, g in zip(ins, grads):
            if g is None:
                raise RuntimeError(
                    f"One of the differentiated tensors ({t.name}) appears unused; "
                    "pass allow_unused=True to get None for it")
    return grads
