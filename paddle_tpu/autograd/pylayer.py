"""PyLayer: user-defined autograd functions.

Capability parity with reference paddle/fluid/eager/pylayer/ +
python/paddle/autograd/py_layer.py. The custom backward runs through the
dispatcher, so its ops are themselves jax lowerings (traceable, fusable).
"""
from __future__ import annotations

from typing import Any, List

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .engine import AccumulationNode, GradNode


class PyLayerContext:
    def __init__(self):
        self._saved: List[Tensor] = []
        self.non_differentiable = ()
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return self._saved

    @property
    def saved_tensors(self):
        return tuple(self._saved)

    def mark_non_differentiable(self, *tensors):
        self.non_differentiable = tensors

    def set_materialize_grads(self, value: bool):
        self._materialize_grads = value


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Subclass with static ``forward(ctx, *args)`` / ``backward(ctx, *grads)``."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core import dispatch

        ctx = PyLayerContext()

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        with dispatch.no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)

        requires = [not t.stop_gradient for t in tensor_inputs]
        record = dispatch.grad_enabled() and any(requires)
        if record:
            node = _PyLayerGradNode(cls, ctx, tensor_inputs, out_list, requires)
            for i, o in enumerate(out_list):
                if isinstance(o, Tensor) and o not in ctx.non_differentiable:
                    o.stop_gradient = False
                    o.grad_node = node
                    o.output_index = i
        return outs


class _PyLayerGradNode(GradNode):
    """GradNode whose vjp is the user's backward()."""

    __slots__ = ("cls", "ctx")

    def __init__(self, cls, ctx, tensor_inputs, out_list, requires):
        edges = []
        for t, req in zip(tensor_inputs, requires):
            if not req:
                edges.append((None, 0))
            elif t.grad_node is not None:
                edges.append((t.grad_node, t.output_index))
            else:
                if getattr(t, "_accum_node", None) is None:
                    t._accum_node = AccumulationNode(t)
                edges.append((t._accum_node, 0))
        out_avals = [(tuple(o.shape), np.dtype(o.dtype)) if isinstance(o, Tensor)
                     else ((), np.dtype(np.float32)) for o in out_list]
        super().__init__(f"pylayer_{cls.__name__}", self._run_backward, edges,
                         out_avals, requires, out_tuple=len(out_list) > 1)
        self.cls = cls
        self.ctx = ctx

    def _run_backward(self, cts):
        if not isinstance(cts, tuple):
            cts = (cts,)
        grad_ts = [Tensor(c) if not isinstance(c, Tensor) else c for c in cts]
        outs = self.cls.backward(self.ctx, *grad_ts)
        if not isinstance(outs, (tuple, list)):
            outs = [outs]
        return tuple(o._data if isinstance(o, Tensor) else o for o in outs)
