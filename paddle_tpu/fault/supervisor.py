"""Fleet supervisor — detect, name the culprit, abort coordinated, rewind.

A pod dies by its weakest rank: a SIGKILLed or wedged worker leaves every
peer blocked *inside* a collective until the cluster scheduler's patience
runs out, and a restarted job can resume ranks at different checkpoint
steps (split brain).  The diagnosis half already exists — flight recorder
+ ``diff_ranks``, watchdog, beacon, sentinel, goodput rewind ledger —
and this module is the half that *acts* on those signals.  Four pieces:

**Collective-timeout abort plane.**  ``FLAGS_collective_timeout_s`` arms
a monitor thread over the flight recorder's ring: every collective
already opens an in-flight entry before the device op (``_coll_begin``)
and stamps it closed on completion, so an entry open past the deadline
IS the hang evidence — no hot-path change, zero cost when disarmed (the
flag defaults to 0 and the thread does not exist).  On fire the monitor
persists this rank's ring, waits briefly for peer dumps, runs
:func:`flight.diff_ranks` with the full world (a SIGKILLed peer leaves
no dump, and that absence names it), prints the verdict, and force-exits
with :data:`EXIT_COLLECTIVE_TIMEOUT` (or :data:`EXIT_DESYNC` when the
diff proves a rank raced/bypassed).

**Rank-failure detection.**  Lease-based heartbeats: every rank publishes
a stamp each supervisor tick (:class:`FileLease` on a shared directory,
or :class:`KVLease` through the launch KV master, whose server-side
clock defeats cross-host skew).  The :class:`Supervisor` declares a rank
dead on lease expiry and force-exits the survivors with
:data:`EXIT_HEARTBEAT_LOST` — a coordinated abort the elastic launcher
can restart, instead of an indefinite block.  The same loop hosts the
drillable fault points ``rank.crash_at_step`` / ``rank.hang_at_step`` /
``heartbeat.lease_lost``.

**Coordinated consensus rewind.**  On restart, ranks exchange their
:class:`~.checkpoint_manager.CheckpointManager` manifest steps (one
fixed-shape ``gather_rows``, or the KV server when collectives aren't up
yet) and resume from the *maximum step completed on every rank* —
:func:`consensus_step` / :func:`consensus_resume`.  The recomputed steps
are billed to the goodput ledger's ``rewind`` bucket through the
existing ``note_resume`` seam.

**Sentinel remediation.**  ``FLAGS_remediation`` gates a registry of
bounded, audited actions keyed by sentinel incident kind —
``compile_storm`` → pcc warmup from the manifest,
``data_stall_regression`` → raise ``FLAGS_prefetch_depth``,
``nonfinite_loss`` → GradScaler backoff (joining the hapi skip-step
path) — each rate-limited, counted in
``paddle_tpu_fault_remediations_total{kind,action}``, with an optional
per-incident chrome-trace capture (``PADDLE_TPU_INCIDENT_TRACE``).

Exit-code taxonomy (the elastic agent's restart-worthiness contract):

=====  =====================  ==============  =============================
code   name                   restart-worthy  meaning
=====  =====================  ==============  =============================
113    CONFIG                 no              bad flags/arguments — a
                                              restart would fail identically
117    COLLECTIVE_TIMEOUT     yes             a collective stayed open past
                                              ``FLAGS_collective_timeout_s``
                                              (peer dead or wedged)
118    HEARTBEAT_LOST         yes             a rank's lease expired (or our
                                              own did — partitioned)
119    DESYNC                 yes             the cross-rank flight diff
                                              proved a rank raced/bypassed a
                                              collective
120    WATCHDOG_HANG          yes             the progress watchdog fired
                                              with no cross-rank desync
                                              verdict
=====  =====================  ==============  =============================

Signal deaths (negative ``Popen`` codes) and generic crashes are
restart-worthy; ``argparse``'s 2 and :data:`EXIT_CONFIG` are not.
"""
from __future__ import annotations

import json
import os
import queue
import signal
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core import flags
from ..observability import flight as _flight
from ..observability import metrics as _metrics
from . import inject as _inject

__all__ = [
    "EXIT_CONFIG", "EXIT_COLLECTIVE_TIMEOUT", "EXIT_HEARTBEAT_LOST",
    "EXIT_DESYNC", "EXIT_WATCHDOG_HANG", "EXIT_CODES",
    "describe_exit", "restart_worthy", "force_exit",
    "FileLease", "KVLease", "Supervisor", "get", "tick",
    "elastic_agent_loop",
    "consensus_step", "consensus_resume",
    "RemediationEngine", "enable_remediation", "remediation_engine",
    "register_scaler", "INCIDENT_TRACE_ENV",
]

# --------------------------------------------------------------- exit codes
EXIT_CONFIG = 113
EXIT_COLLECTIVE_TIMEOUT = 117
EXIT_HEARTBEAT_LOST = 118
EXIT_DESYNC = 119
EXIT_WATCHDOG_HANG = 120

#: code -> (name, restart_worthy, description)
EXIT_CODES: Dict[int, tuple] = {
    EXIT_CONFIG: (
        "CONFIG", False,
        "configuration error — restarting would fail identically"),
    EXIT_COLLECTIVE_TIMEOUT: (
        "COLLECTIVE_TIMEOUT", True,
        "a collective stayed open past FLAGS_collective_timeout_s"),
    EXIT_HEARTBEAT_LOST: (
        "HEARTBEAT_LOST", True,
        "a rank's heartbeat lease expired"),
    EXIT_DESYNC: (
        "DESYNC", True,
        "cross-rank flight diff named a desynced rank"),
    EXIT_WATCHDOG_HANG: (
        "WATCHDOG_HANG", True,
        "progress watchdog fired without a cross-rank desync verdict"),
}


def describe_exit(code: Optional[int]) -> str:
    """Human-readable name for a worker exit code (signal deaths are the
    negative codes ``subprocess`` reports)."""
    if code is None:
        return "running"
    if code in EXIT_CODES:
        name, _, desc = EXIT_CODES[code]
        return f"{name} ({desc})"
    if code < 0:
        try:
            return f"signal {signal.Signals(-code).name}"
        except ValueError:
            return f"signal {-code}"
    return f"exit {code}"


def restart_worthy(code: Optional[int]) -> bool:
    """Whether the elastic agent should spend a restart on this death.

    Signal deaths (SIGKILL'd by the OOM killer, a preempted VM) and the
    supervisor's fault codes are transient-by-construction; config errors
    (:data:`EXIT_CONFIG`, argparse's 2) would fail identically on every
    retry and must stop the job immediately."""
    if code is None or code == 0:
        return False
    if code in EXIT_CODES:
        return EXIT_CODES[code][1]
    if code == 2:               # argparse usage error
        return False
    return True                 # signal deaths + generic crashes


#: replaceable exit hook so in-process tests can observe force_exit
#: without dying (the real path MUST be os._exit: atexit handlers may
#: touch the wedged backend and hang the abort itself)
_exit = {"fn": os._exit}


def force_exit(code: int, reason: str = ""):
    """Terminal abort: persist the flight ring and the goodput ledger
    (``os._exit`` skips atexit), flush, and exit with ``code``.  The
    goodput dump carries ``last_step``, which is how the relaunched
    process's ``note_resume`` learns how far this one had progressed —
    the rewind bucket's crash-side half."""
    try:
        sys.stderr.write(f"[supervisor] force exit code={code} "
                         f"({describe_exit(code)}): {reason}\n")
    except Exception:
        pass
    try:
        _flight.dump(reason=f"force_exit {code}: {reason}")
    except Exception:
        pass
    try:
        from ..observability import goodput as _goodput
        _goodput.dump(reason=f"force_exit {code}: {reason}")
    except Exception:
        pass
    try:
        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:
        pass
    _exit["fn"](code)


# ------------------------------------------- collective-timeout abort plane
flags.define_flag(
    "collective_timeout_s", 0.0,
    "Abort (with exit code 117/119) when a collective's flight-recorder "
    "entry stays open past this many seconds — a dead or wedged peer. "
    "0 disarms: no monitor thread exists and the hot path is unchanged.")

_monitor: Dict[str, object] = {"thread": None, "stop": None}
_monitor_lock = threading.Lock()


def _monitor_loop(stop: threading.Event):
    while True:
        try:
            t = float(flags.get_flag("collective_timeout_s") or 0.0)
        except Exception:
            return
        poll = min(max(t / 4.0, 0.05), 0.5) if t > 0 else 0.5
        if stop.wait(poll):
            return
        if t <= 0:
            continue
        now = time.perf_counter()
        overdue = [r for r in _flight.RECORDER.open_entries()
                   if now - r["t0"] > t]
        if overdue:
            rec = min(overdue, key=lambda r: r["t0"])
            _abort_on_timeout(rec, now - rec["t0"], t)
            return


def _abort_on_timeout(rec: dict, age: float, timeout_s: float):
    """One overdue collective: print the local evidence, exchange flight
    dumps out-of-band, name the culprit, exit.  Runs on the monitor
    thread while the main thread is still blocked inside the op."""
    err = sys.stderr
    rank, world = _flight.rank_world()
    err.write(f"[supervisor] rank {rank}: collective seq={rec['seq']} "
              f"op={rec['op']} group={rec.get('group', 0)} open for "
              f"{age:.1f}s > FLAGS_collective_timeout_s={timeout_s:g}\n")
    code = EXIT_COLLECTIVE_TIMEOUT
    base = os.environ.get(_flight.RECORD_ENV)
    if base and world > 1:
        _flight.dump(reason=f"collective timeout seq={rec['seq']}")
        # peers' monitors fire within one poll of ours; a dead peer never
        # writes, so the wait is bounded and its absence is the evidence
        deadline = time.monotonic() + min(timeout_s + 2.0, 15.0)
        dumps = _flight.load_dumps(base, world=world)
        while len(dumps) < world and time.monotonic() < deadline:
            time.sleep(0.25)
            dumps = _flight.load_dumps(base, world=world)
        verdict = _flight.diff_ranks(dumps, world=world)
        err.write(f"[supervisor] cross-rank flight diff "
                  f"({len(dumps)}/{world} rank dumps): "
                  f"status={verdict['status']}"
                  + (f" rank={verdict['rank']}"
                     if verdict.get("rank") is not None else "")
                  + (f" seq={verdict['seq']}"
                     if verdict.get("seq") is not None else "")
                  + f"\n[supervisor] {verdict['detail']}\n")
        if verdict["status"] == "desync":
            code = EXIT_DESYNC
    force_exit(code, reason=f"collective seq={rec['seq']} ({rec['op']}) "
                            f"open > {timeout_s:g}s")


def _sync_monitor(value):
    """Start/stop the monitor thread to track the flag — the disarmed
    state has NO thread, so the zero-cost claim is structural."""
    t = float(value or 0.0)
    with _monitor_lock:
        if t > 0 and _monitor["thread"] is None:
            stop = threading.Event()
            th = threading.Thread(
                target=_monitor_loop, args=(stop,), daemon=True,
                name="paddle_tpu_collective_timeout")
            _monitor["thread"], _monitor["stop"] = th, stop
            th.start()
        elif t <= 0 and _monitor["thread"] is not None:
            _monitor["stop"].set()
            _monitor["thread"], _monitor["stop"] = None, None


flags.on_change("collective_timeout_s", _sync_monitor)
_sync_monitor(flags.get_flag("collective_timeout_s"))


# ------------------------------------------------------ rank-failure leases
class FileLease:
    """Per-rank lease stamps in a shared directory (single-host groups or
    a shared filesystem).  Staleness is judged relative to the freshest
    stamp — like the KV heartbeat's server clock, this makes a slow
    *observer* unable to fake everyone else's death: only a rank whose
    stamp lags its liveliest peer by ``ttl`` is dead."""

    def __init__(self, directory: str, rank: Optional[int] = None,
                 world: Optional[int] = None, ttl: float = 10.0):
        r, w = _flight.rank_world()
        self.directory = str(directory)
        self.rank = int(rank) if rank is not None else r
        self.world = int(world) if world is not None else w
        self.ttl = float(ttl)
        self.path = os.path.join(self.directory, f"lease.r{self.rank}")

    def publish(self):
        os.makedirs(self.directory, exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(repr(time.time()))
        os.replace(tmp, self.path)

    def stamps(self) -> Dict[int, float]:
        out: Dict[int, float] = {}
        for r in range(self.world):
            p = os.path.join(self.directory, f"lease.r{r}")
            try:
                with open(p) as f:
                    out[r] = float(f.read().strip())
            except (OSError, ValueError):
                continue
        return out

    def dead_ranks(self) -> List[int]:
        stamps = self.stamps()
        if not stamps:
            return []
        freshest = max(stamps.values())
        return sorted(r for r, ts in stamps.items()
                      if freshest - ts > self.ttl)


class KVLease:
    """Lease through the launch KV master (``launch/kv_server.py``):
    stamps are server-clocked (``X-KV-Stamp: server``), so cross-host
    clock skew cannot fake a death.  The multi-host backend."""

    def __init__(self, master: str, rank: Optional[int] = None,
                 world: Optional[int] = None, job_id: str = "default",
                 ttl: float = 10.0):
        from ..distributed.launch.kv_server import Heartbeat
        r, w = _flight.rank_world()
        self.rank = int(rank) if rank is not None else r
        self.world = int(world) if world is not None else w
        self.ttl = float(ttl)
        self._hb = Heartbeat(master, self.rank, job_id=job_id, ttl=ttl)

    def publish(self):
        self._hb.client.put(self._hb.key, b"", server_stamp=True)

    def stamps(self) -> Dict[int, float]:
        return self._hb.stamps()

    def dead_ranks(self) -> List[int]:
        return self._hb.dead_nodes()


class Supervisor:
    """In-process rank-failure detector.

    A background loop publishes this rank's lease every ``interval`` and
    judges peers; the training loop additionally calls :meth:`beat` each
    step (opportunistic freshness + the drillable fault points).  On
    lease expiry — a peer's, or our OWN (we are the partitioned side) —
    the survivors abort coordinated with :data:`EXIT_HEARTBEAT_LOST`
    instead of blocking in the next collective."""

    def __init__(self, lease, interval: float = 1.0,
                 on_dead: Optional[Callable[[List[int]], None]] = None,
                 exit_on_dead: bool = True):
        self.lease = lease
        self.interval = float(interval)
        self.on_dead = on_dead
        self.exit_on_dead = exit_on_dead
        self.dead: List[int] = []
        self._suspended = threading.Event()   # heartbeat.lease_lost drill
        self._last_pub = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _publish(self):
        try:
            self.lease.publish()
            self._last_pub = time.monotonic()
        except Exception:
            pass

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "Supervisor":
        self._publish()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="paddle_tpu_supervisor")
        self._thread.start()
        _default["s"] = self
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval)
            self._thread = None
        if _default.get("s") is self:
            _default["s"] = None

    def _loop(self):
        # let every peer's first stamp land before judging
        if self._stop.wait(2 * self.interval):
            return
        while not self._stop.wait(self.interval):
            if not self._suspended.is_set():
                self._publish()
            try:
                dead = self.lease.dead_ranks()
            except Exception:
                continue
            if dead:
                self._handle_dead(dead)
                return

    def _handle_dead(self, dead: List[int]):
        self.dead = list(dead)
        me = getattr(self.lease, "rank", None)
        ttl = getattr(self.lease, "ttl", 0.0)
        msg = (f"rank(s) {dead} lease expired (ttl={ttl:g}s)"
               + (" — including OWN lease (partitioned)"
                  if me in dead else ""))
        if self.on_dead is not None:
            try:
                self.on_dead(list(dead))
            except Exception:
                pass
        if self.exit_on_dead:
            sys.stderr.write(f"[supervisor] rank {me}: {msg} — "
                             f"aborting coordinated\n")
            force_exit(EXIT_HEARTBEAT_LOST, reason=msg)

    # ----------------------------------------------------------- step tick
    def beat(self, step: Optional[int] = None):
        """Per-step feed from the training loop.  Publishes the lease
        opportunistically and hosts the fault drills; one module-dict
        truthiness check when nothing is armed."""
        if _inject.fire("rank.crash_at_step", step=step) is not None:
            sys.stderr.write(f"[supervisor] rank.crash_at_step fired at "
                             f"step {step}: SIGKILL\n")
            sys.stderr.flush()
            os.kill(os.getpid(), signal.SIGKILL)
        if _inject.fire("rank.hang_at_step", step=step) is not None:
            sys.stderr.write(f"[supervisor] rank.hang_at_step fired at "
                             f"step {step}: wedging this rank (leases "
                             f"stay fresh — only the collective-timeout "
                             f"plane can catch this)\n")
            sys.stderr.flush()
            while True:             # a wedged host; SIGTERM still lands
                time.sleep(3600)
        if _inject.fire("heartbeat.lease_lost", step=step) is not None:
            sys.stderr.write(f"[supervisor] heartbeat.lease_lost fired at "
                             f"step {step}: suspending lease publishing "
                             f"(process stays alive)\n")
            sys.stderr.flush()
            self._suspended.set()
        # opportunistic publish, RATE-LIMITED to half the loop interval:
        # it only matters when the background thread is starved (a GIL-
        # hogging step), and an unconditional per-step file write would
        # cost hundreds of µs — the disarmed-overhead budget's worth
        if (not self._suspended.is_set()
                and time.monotonic() - self._last_pub
                >= 0.5 * self.interval):
            self._publish()


_default: Dict[str, Optional[Supervisor]] = {"s": None}


def get() -> Optional[Supervisor]:
    """The process's active supervisor (the last one started), if any."""
    return _default["s"]


def tick(step: Optional[int] = None):
    """Training-loop seam: forward one step tick to the active
    supervisor.  One dict lookup when none is running."""
    s = _default["s"]
    if s is not None:
        s.beat(step)


# --------------------------------------------------------- elastic agent
def elastic_agent_loop(manager, initial_world: List[int],
                       stop_event: threading.Event):
    """The elastic agent's membership loop (node 0) — hoisted out of
    ``ElasticManager.start`` so the supervisor IS the agent: the same
    lease-expiry judgement drives both the in-process coordinated abort
    and the launcher-level rescale/fail decision.  ``decide()`` stays a
    pure function on the manager for unit tests."""
    # let every peer's first heartbeat land before judging
    time.sleep(manager.heartbeat.interval * 2)
    while not stop_event.wait(manager.interval):
        known = manager.current_world() or initial_world
        action, new_world = manager.decide(known, manager.live_peers())
        if action == "rescale":
            epoch = manager.publish(new_world)
            print(f"[elastic] membership {known} -> {new_world}; "
                  f"epoch {epoch}")
        elif action == "fail":
            manager.mark_failed(f"below quorum: live={new_world}, "
                                f"min={manager.min_nodes}")
            print(f"[elastic] job below quorum ({new_world}); "
                  f"marking failed")
            return


# --------------------------------------------------- consensus rewind
#: manifest steps exchanged per rank (newest first, -1 padded) — fixed
#: shape so the gather is one cached compiled program
CONSENSUS_K = 8


def consensus_step(local_steps: List[int], rank: Optional[int] = None,
                   world: Optional[int] = None, k: int = CONSENSUS_K,
                   kv: Optional[str] = None, job_id: str = "default",
                   epoch: Optional[int] = None,
                   timeout: float = 30.0) -> Optional[int]:
    """The *maximum step completed on every rank* — the split-brain-free
    resume point.

    Each rank contributes its newest ``k`` manifest steps; the consensus
    is the largest step present in EVERY rank's set (None when the sets
    share nothing — resume from scratch rather than diverge).  Transport
    is one fixed-shape :func:`gather_rows` when the collectives are up;
    pass ``kv="host:port"`` to exchange through the launch KV master
    instead (restart paths where no jax world exists yet)."""
    r, w = _flight.rank_world()
    rank = int(rank) if rank is not None else r
    world = int(world) if world is not None else w
    mine = sorted(set(int(s) for s in local_steps), reverse=True)[:k]
    if world <= 1:
        return mine[0] if mine else None
    if kv is not None:
        sets = _consensus_kv(kv, rank, world, mine, job_id, epoch, timeout)
    else:
        from ..distributed.communication.collective import gather_rows
        row = np.full(k + 1, -1.0, np.float32)
        row[0] = float(rank)
        row[1:1 + len(mine)] = mine
        mat = gather_rows(row)
        sets = [set(int(v) for v in mat[i, 1:] if v >= 0)
                for i in range(mat.shape[0])]
    common = set.intersection(*sets) if sets else set()
    return max(common) if common else None


def _consensus_kv(master: str, rank: int, world: int, mine: List[int],
                  job_id: str, epoch: Optional[int],
                  timeout: float) -> List[set]:
    """KV-transport manifest exchange: publish under
    ``/consensus/<job>/e<epoch>/<rank>``, poll until every rank arrived.
    The epoch scopes the keys so a second restart never reads the first
    restart's stale manifests."""
    from ..distributed.launch.kv_server import KVClient
    if epoch is None:
        epoch = int(os.environ.get("PADDLE_ELASTIC_EPOCH", "0") or 0)
    client = KVClient(master)
    prefix = f"/consensus/{job_id}/e{epoch}"
    payload = json.dumps(mine)
    deadline = time.monotonic() + timeout
    while not client.put(f"{prefix}/{rank}", payload):
        if time.monotonic() > deadline:
            raise ConnectionError(
                f"consensus: cannot reach KV master {master}")
        time.sleep(0.3)
    want = {f"{prefix}/{r}" for r in range(world)}
    while time.monotonic() < deadline:
        have = client.get_prefix(prefix)
        if want <= set(have):
            out = []
            for r in range(world):
                try:
                    out.append(set(int(s)
                               for s in json.loads(have[f"{prefix}/{r}"])))
                except (ValueError, KeyError):
                    out.append(set())
            return out
        time.sleep(0.3)
    missing = sorted(int(k.rsplit("/", 1)[1])
                     for k in (want - set(client.get_prefix(prefix))))
    raise TimeoutError(
        f"consensus: ranks {missing} never published manifests "
        f"within {timeout}s")


def consensus_resume(manager, network=None, optimizer=None, scaler=None,
                     verify: bool = True, kv: Optional[str] = None,
                     job_id: str = "default",
                     timeout: float = 30.0) -> Optional[dict]:
    """:func:`~.checkpoint_manager.auto_resume` bounded by the cross-rank
    consensus step.  Single-process worlds degrade to plain auto_resume;
    the rewind (consensus step → the crashed run's last step) is billed
    by ``note_resume`` exactly as before — same seam, tighter bound."""
    from .checkpoint_manager import auto_resume
    rank, world = _flight.rank_world()
    max_step = None
    if world > 1:
        max_step = consensus_step(manager.steps(), kv=kv, job_id=job_id,
                                  timeout=timeout)
        local = manager.steps()
        newest = local[0] if local else None
        sys.stderr.write(f"[supervisor] rank {rank}: consensus resume "
                         f"step={max_step} (local newest {newest})\n")
    return auto_resume(manager, network=network, optimizer=optimizer,
                       scaler=scaler, verify=verify, max_step=max_step)


# ------------------------------------------------- sentinel remediation
flags.define_flag(
    "remediation", False,
    "Sentinel-driven bounded remediation: compile_storm -> pcc warmup, "
    "data_stall_regression -> raise prefetch depth, nonfinite_loss -> "
    "GradScaler backoff. Off: incidents are observed, never acted on.")

#: env var naming a directory for per-incident chrome-trace captures
INCIDENT_TRACE_ENV = "PADDLE_TPU_INCIDENT_TRACE"

M_REMEDIATIONS = _metrics.counter(
    "paddle_tpu_fault_remediations_total",
    "Remediation actions taken by the supervisor, by incident kind and "
    "action (skipped/rate-limited attempts are not counted).",
    labelnames=("kind", "action"))

_scaler_ref: Dict[str, object] = {"s": None}


def register_scaler(scaler):
    """Hand the remediation engine the run's GradScaler (the hapi fit
    path registers the one its ModelCheckpoint callback carries)."""
    _scaler_ref["s"] = scaler


class RemediationEngine:
    """Bounded, audited incident→action dispatch.

    Sentinel incidents arrive on the sentinel's own lock, so ``submit``
    only enqueues; a daemon worker runs the action.  Every action is
    rate-limited per kind (``min_interval_s``), capped per kind
    (``max_per_kind``), counted in the remediation metric, appended to
    the ``audit`` list, and — when ``PADDLE_TPU_INCIDENT_TRACE`` names a
    directory and no profiler session is active — captured as a
    per-incident chrome trace."""

    #: incident kind -> action name (the registry)
    ACTIONS = {
        "compile_storm": "pcc_warmup",
        "data_stall_regression": "raise_prefetch_depth",
        "nonfinite_loss": "scaler_backoff",
    }

    def __init__(self, min_interval_s: float = 30.0,
                 max_per_kind: int = 8):
        self.min_interval_s = float(min_interval_s)
        self.max_per_kind = int(max_per_kind)
        self.audit: List[dict] = []
        self._q: "queue.Queue[dict]" = queue.Queue()
        self._last: Dict[str, float] = {}
        self._count: Dict[str, int] = {}
        self._trace_n = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "RemediationEngine":
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="paddle_tpu_remediation")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def submit(self, incident: dict):
        """Sentinel observer — called under the sentinel's lock, so this
        must only enqueue."""
        if not flags.get_flag("remediation"):
            return
        if incident.get("kind") in self.ACTIONS:
            self._q.put(dict(incident))

    def drain(self, timeout: float = 2.0):
        """Block until the queue is empty and in-flight work finished
        (tests)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._q.unfinished_tasks == 0:
                return
            time.sleep(0.02)

    # ------------------------------------------------------------ worker
    def _loop(self):
        while not self._stop.is_set():
            try:
                inc = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._handle(inc)
            except Exception:
                pass
            finally:
                self._q.task_done()

    def _handle(self, inc: dict):
        kind = inc["kind"]
        action = self.ACTIONS[kind]
        now = time.monotonic()
        last = self._last.get(kind)
        entry = {"kind": kind, "action": action,
                 "step": inc.get("step"), "detail": None, "ok": False,
                 "t": time.time()}
        if last is not None and now - last < self.min_interval_s:
            entry["detail"] = (f"rate-limited (last {action} "
                              f"{now - last:.1f}s ago < "
                              f"{self.min_interval_s:g}s)")
            self.audit.append(entry)
            return
        if self._count.get(kind, 0) >= self.max_per_kind:
            entry["detail"] = (f"suppressed: {kind} already remediated "
                              f"{self.max_per_kind} times this run")
            self.audit.append(entry)
            return
        self._last[kind] = now
        self._count[kind] = self._count.get(kind, 0) + 1
        from ..observability import trace as _trace
        capture = (not _trace.active()
                   and bool(os.environ.get(INCIDENT_TRACE_ENV)))
        if capture:
            _trace.activate()
        t0 = time.perf_counter()
        try:
            ok, detail = self._run(kind)
        except Exception as e:
            ok, detail = False, f"{action} raised {type(e).__name__}: {e}"
        t1 = time.perf_counter()
        _trace.add_complete(f"remediation:{action}", "fault", t0, t1,
                            {"kind": kind, "step": inc.get("step")})
        if capture:
            events = _trace.drain()
            _trace.deactivate()
            self._persist_trace(kind, action, events)
        entry["ok"], entry["detail"] = ok, detail
        self.audit.append(entry)
        M_REMEDIATIONS.inc(kind=kind, action=action)
        try:
            sys.stderr.write(
                f"[supervisor] remediation {action} for {kind} @ step "
                f"{inc.get('step')}: {detail}\n")
        except Exception:
            pass

    def _run(self, kind: str):
        if kind == "compile_storm":
            from ..compile import warmup as _warmup
            path = _warmup.manifest_path()
            if not path:
                return False, "no compile-cache manifest configured"
            res = _warmup.warm(path)
            return True, (f"pcc warmup from manifest: "
                          f"{len(res.get('warmed', []))} warmed, "
                          f"{len(res.get('skipped', []))} skipped, "
                          f"{len(res.get('failed', []))} failed")
        if kind == "data_stall_regression":
            cur = int(flags.get_flag("prefetch_depth") or 0)
            if cur >= 8:
                return False, f"prefetch_depth already {cur} (cap 8)"
            flags.set_flags({"prefetch_depth": cur + 1})
            return True, (f"prefetch_depth {cur} -> {cur + 1} "
                          f"(takes effect at the next prefetcher build)")
        if kind == "nonfinite_loss":
            s = _scaler_ref["s"]
            if s is None:
                return False, ("no GradScaler registered "
                               "(hapi skip-step already dropped the "
                               "poisoned grads)")
            old = float(getattr(s, "_scale", 0.0) or 0.0)
            if old <= 1.0:
                return False, f"loss scale already at floor ({old:g})"
            new = max(old / 2.0, 1.0)
            s._scale = new
            return True, (f"loss-scale backoff {old:g} -> {new:g} "
                          f"(joins the hapi skip-step path)")
        return False, f"no action for {kind}"

    def _persist_trace(self, kind: str, action: str, events):
        base = os.environ.get(INCIDENT_TRACE_ENV)
        if not base:
            return
        try:
            os.makedirs(base, exist_ok=True)
            self._trace_n += 1
            out = [{"name": n, "cat": c, "ph": "X",
                    "ts": t0 * 1e6, "dur": (t1 - t0) * 1e6,
                    "pid": os.getpid(), "tid": tid, "args": args or {}}
                   for (n, c, t0, t1, tid, args) in events]
            path = os.path.join(
                base, f"incident-{self._trace_n:03d}-{kind}.trace.json")
            with open(path, "w") as f:
                json.dump({"traceEvents": out,
                           "displayTimeUnit": "ms",
                           "incident": {"kind": kind, "action": action}},
                          f)
        except Exception:
            pass


_engine: Dict[str, Optional[RemediationEngine]] = {"e": None}


def remediation_engine() -> Optional[RemediationEngine]:
    return _engine["e"]


def _ensure_engine(min_interval_s: float = 30.0,
                   max_per_kind: int = 8) -> RemediationEngine:
    if _engine["e"] is None:
        from ..observability import sentinel as _sentinel
        eng = RemediationEngine(min_interval_s=min_interval_s,
                                max_per_kind=max_per_kind).start()
        _engine["e"] = eng
        _sentinel.on_incident(eng.submit)
    return _engine["e"]


def enable_remediation(min_interval_s: float = 30.0,
                       max_per_kind: int = 8) -> RemediationEngine:
    """Turn the remediation plane on: starts the worker, registers the
    sentinel observer, sets ``FLAGS_remediation``.  Idempotent.  The
    engine is built BEFORE the flag flips: the flag observer runs under
    the flags registry lock and must not call back into set_flags."""
    eng = _ensure_engine(min_interval_s=min_interval_s,
                         max_per_kind=max_per_kind)
    if not flags.get_flag("remediation"):
        flags.set_flags({"remediation": True})
    return eng


def disable_remediation():
    if flags.get_flag("remediation"):
        flags.set_flags({"remediation": False})
    eng = _engine["e"]
    if eng is not None:
        try:
            from ..observability import sentinel as _sentinel
            _sentinel.remove_incident_observer(eng.submit)
        except Exception:
            pass
        eng.stop()
        _engine["e"] = None


def _remediation_flag_changed(v):
    # runs under the flags registry lock — build the engine, never call
    # back into set_flags from here
    if v:
        _ensure_engine()


flags.on_change("remediation", _remediation_flag_changed)
if flags.get_flag("remediation"):
    _ensure_engine()
