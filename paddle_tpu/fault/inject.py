"""Deterministic fault injection — named failure points for recovery tests.

Production code guards a risky operation with ``inject.check("name")`` (or
reads parameters via ``inject.peek``); when nothing is armed the guard is
one module-dict truthiness test, so the cost in real runs is effectively
zero. Tests arm a point for a bounded number of shots and prove the
recovery path end-to-end — crash-mid-save leaves the old checkpoint
intact, resume skips a corrupt latest, retry exhaustion surfaces the
original error — without monkeypatching internals or sleeping.

Every point is deterministic: it fires on the first ``times`` matching
calls and never again, and arming an unknown name is an error (typo
guard). The registered points:

==================================  =========================================
``io.write_truncate_after_bytes``   checkpoint writer stops mid-file after
                                    ``after_bytes`` bytes (simulated crash /
                                    full disk); params: ``after_bytes``
``io.rename_fail``                  the atomic ``os.replace`` publish step
                                    raises ``OSError``
``io.fsync_fail``                   the pre-publish fsync raises ``OSError``
``collective.timeout``              host-side object collectives raise
                                    ``TimeoutError`` (stuck peer)
``grads.nan_at_step``               the training loop poisons the loss with
                                    NaN at global step ``step``
``pcc.write_truncate_after_bytes``  the compilation-cache entry writer stops
                                    mid-file after ``after_bytes`` bytes
                                    (torn cache publish); params:
                                    ``after_bytes``
``serving.tick_stall``              the serving engine tick blocks for
                                    ``seconds`` before doing any work (a
                                    wedged device transfer / compile) —
                                    exercises the watchdog → DEGRADED path;
                                    params: ``seconds``
``serving.admission_oom``           admission-time block allocation is forced
                                    to fail as if another slot raced it to
                                    the last KV blocks — exercises the
                                    requeue-not-raise path
``serving.crash_at_tick``           an unexpected exception is raised inside
                                    the engine tick whose ordinal equals
                                    ``tick`` — exercises the fail-in-flight
                                    + degrade + keep-serving path; params:
                                    ``tick``
``fleet.slow_step``                 the fleet beacon sleeps ``seconds``
                                    inside each observed training step —
                                    the deterministic slow-rank drill for
                                    straggler detection (arm on ONE rank);
                                    params: ``seconds``
``collective.desync``               a shape-preserving tensor collective
                                    (``all_reduce`` / ``all_gather`` /
                                    ``broadcast`` / ``barrier``) is BYPASSED
                                    on this rank (peers block on the missing
                                    participant) — the deterministic desync
                                    drill for the flight-recorder diff;
                                    params: optional ``op`` filter. Other
                                    primitives change output shape under a
                                    bypass and are not wired.
``rank.crash_at_step``              the supervisor heartbeat kills this
                                    process with SIGKILL (no atexit, no
                                    dump — a real machine death) at global
                                    step ``step``; params: ``step``
``rank.hang_at_step``               the supervisor heartbeat wedges this
                                    rank in an uninterruptible sleep at
                                    global step ``step`` (peers block in the
                                    next collective) — the deterministic
                                    hang drill for the collective-timeout
                                    abort plane; params: ``step``
``heartbeat.lease_lost``            the supervisor stops publishing this
                                    rank's heartbeat lease (process stays
                                    alive — a network partition, not a
                                    death) so peers observe lease expiry;
                                    params: optional ``step``
==================================  =========================================
"""
from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional

__all__ = ["InjectedFault", "POINTS", "arm", "disarm", "disarm_all",
           "is_armed", "fired_count", "peek", "fire", "check", "armed"]


class InjectedFault(Exception):
    """Raised by a firing fault point (unless the guard maps it to a more
    faithful exception type, e.g. OSError for filesystem points)."""

    def __init__(self, point: str, message: str = ""):
        self.point = point
        super().__init__(message or f"injected fault at {point!r}")


#: the full set of known failure points — arming anything else is an error
POINTS = frozenset({
    "io.write_truncate_after_bytes",
    "io.rename_fail",
    "io.fsync_fail",
    "collective.timeout",
    "grads.nan_at_step",
    "pcc.write_truncate_after_bytes",
    "serving.tick_stall",
    "serving.admission_oom",
    "serving.crash_at_tick",
    "fleet.slow_step",
    "collective.desync",
    "rank.crash_at_step",
    "rank.hang_at_step",
    "heartbeat.lease_lost",
})

_lock = threading.Lock()
# name -> {"times": shots to fire, "fired": shots consumed, "params": {...}}
# The dict is EMPTY whenever nothing is armed, so production guards bail on
# a single truthiness check.
_armed: Dict[str, dict] = {}


def arm(name: str, times: int = 1, **params) -> None:
    """Arm ``name`` to fire on its next ``times`` matching calls."""
    if name not in POINTS:
        raise ValueError(
            f"unknown fault point {name!r}; registered points: "
            f"{sorted(POINTS)}")
    if times < 1:
        raise ValueError(f"times must be >= 1, got {times}")
    with _lock:
        _armed[name] = {"times": int(times), "fired": 0,
                        "params": dict(params)}


def disarm(name: str) -> None:
    with _lock:
        _armed.pop(name, None)


def disarm_all() -> None:
    with _lock:
        _armed.clear()


def is_armed(name: str) -> bool:
    spec = _armed.get(name)
    return bool(spec and spec["fired"] < spec["times"])


def fired_count(name: str) -> int:
    spec = _armed.get(name)
    return spec["fired"] if spec else 0


def peek(name: str, **ctx) -> Optional[dict]:
    """Params of an armed point with shots remaining, WITHOUT consuming a
    shot (for guards that need the parameters up front, e.g. the truncating
    writer reads ``after_bytes`` before any byte is written). Returns None
    when disarmed, out of shots, or the armed params mismatch ``ctx``."""
    if not _armed:
        return None
    spec = _armed.get(name)
    if spec is None or spec["fired"] >= spec["times"]:
        return None
    if not _ctx_matches(spec["params"], ctx):
        return None
    return dict(spec["params"])


def fire(name: str, **ctx) -> Optional[dict]:
    """Consume one shot if ``name`` is armed and its params match ``ctx``
    (every armed param also present in ``ctx`` must compare equal — so
    ``arm("grads.nan_at_step", step=3)`` fires only on the call whose
    ``step=3``). Returns the params dict when the point fires."""
    if not _armed:
        return None
    with _lock:
        spec = _armed.get(name)
        if spec is None or spec["fired"] >= spec["times"]:
            return None
        if not _ctx_matches(spec["params"], ctx):
            return None
        spec["fired"] += 1
        return dict(spec["params"])


def _ctx_matches(params: dict, ctx: dict) -> bool:
    for k, v in params.items():
        if k in ctx and ctx[k] != v:
            return False
    return True


def check(name: str, exc=None, **ctx) -> bool:
    """Production guard: raise when the point fires, else return False.
    ``exc`` maps the injected failure onto the exception type real code
    would see at that site (OSError for filesystem, TimeoutError for a
    stuck collective); default is :class:`InjectedFault`."""
    params = fire(name, **ctx)
    if params is None:
        return False
    if exc is None or (isinstance(exc, type)
                       and issubclass(exc, InjectedFault)):
        raise InjectedFault(name)
    raise exc(f"injected fault at {name!r}")


@contextlib.contextmanager
def armed(name: str, times: int = 1, **params):
    """Scoped arm for tests: disarms on exit even if the body raises."""
    arm(name, times=times, **params)
    try:
        yield
    finally:
        disarm(name)
