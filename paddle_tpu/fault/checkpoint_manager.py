"""CheckpointManager — rotation, manifest, verified resume.

The preemption-tolerant training pattern (PaLM's resume-from-latest,
Megatron-LM's distributed checkpointing): saves land atomically via
:mod:`..framework.io`, a JSON manifest records every COMPLETED save (it
is written only after the checkpoint itself is published, so a crash
between the two leaves a valid orphan checkpoint that restore still
finds by directory scan), rotation keeps the newest ``keep_n``, and
``restore()`` walks newest→oldest, falling back PAST a corrupt or
partial checkpoint to the last verifiable one instead of dying on the
damage. The fallback depth is exported as a metric so a fleet quietly
burning its newest checkpoints shows up on a dashboard, not in a
post-mortem.
"""
from __future__ import annotations

import json
import os
import re
import warnings
from typing import Any, Dict, List, Optional, Tuple

from ..framework import io as _fio
from ..observability import goodput as _goodput
from ..observability import metrics as _metrics
from .retry import RetryPolicy, retry

__all__ = ["CheckpointManager", "auto_resume", "capture_train_state",
           "restore_train_state"]

_MANIFEST = "manifest.json"

_m_fallback_depth = _metrics.gauge(
    "paddle_tpu_resume_fallback_depth",
    "How many newest checkpoints the last restore() had to skip "
    "(0 = newest loaded clean).")
_m_fallback_total = _metrics.counter(
    "paddle_tpu_resume_fallback_total",
    "restore() calls that fell back past at least one bad checkpoint.")
_m_rotated = _metrics.counter(
    "paddle_tpu_ckpt_rotated_total", "Checkpoints deleted by rotation.")


class CheckpointManager:
    """Directory of rotated, atomically-published checkpoints.

    ``save(state, step=...)`` writes ``<prefix>-<step>.pdckpt`` (atomic +
    checksummed, retried on transient OSError), appends the manifest, and
    prunes beyond ``keep_n``. ``restore()`` returns ``(state, meta)``
    from the newest checkpoint that passes verification, skipping any
    that don't.
    """

    def __init__(self, directory: str, keep_n: int = 3,
                 prefix: str = "ckpt",
                 retry_policy: Optional[RetryPolicy] = None,
                 protocol: int = 4):
        if keep_n < 1:
            raise ValueError(f"keep_n must be >= 1, got {keep_n}")
        self.directory = str(directory)
        self.keep_n = int(keep_n)
        self.prefix = prefix
        self.protocol = protocol
        self.retry_policy = retry_policy or RetryPolicy()
        #: fallback depth of the most recent restore(); None before any
        self.last_fallback_depth: Optional[int] = None
        self._pat = re.compile(
            re.escape(prefix) + r"-(\d+)\.pdckpt$")
        os.makedirs(self.directory, exist_ok=True)

    # ------------------------------------------------------------ listing
    def _manifest_path(self) -> str:
        return os.path.join(self.directory, _MANIFEST)

    def manifest(self) -> List[dict]:
        """Entries of completed saves, oldest→newest; tolerant of a
        missing or torn manifest (restore never depends on it)."""
        try:
            with open(self._manifest_path()) as f:
                entries = json.load(f)
            return entries if isinstance(entries, list) else []
        except (OSError, ValueError):
            return []

    def checkpoints(self) -> List[str]:
        """Checkpoint paths newest→oldest, by directory scan (the
        authority on what exists — a save that completed but crashed
        before its manifest append is still found here)."""
        found = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            m = self._pat.match(name)
            if m:
                found.append((int(m.group(1)), name))
        return [os.path.join(self.directory, name)
                for _, name in sorted(found, reverse=True)]

    def latest(self) -> Optional[str]:
        ckpts = self.checkpoints()
        return ckpts[0] if ckpts else None

    def steps(self) -> List[int]:
        """Completed-save steps newest→oldest, by the same directory scan
        as :meth:`checkpoints` — the per-rank manifest the consensus
        rewind exchanges across the fleet."""
        out = []
        for path in self.checkpoints():
            m = self._pat.match(os.path.basename(path))
            if m:
                out.append(int(m.group(1)))
        return out

    # --------------------------------------------------------------- save
    def save(self, state: Any, step: int, epoch: Optional[int] = None,
             meta: Optional[dict] = None) -> str:
        """Atomically publish ``state`` as the checkpoint for ``step``,
        record it in the manifest, then rotate."""
        meta = dict(meta or {})
        meta.setdefault("step", int(step))
        if epoch is not None:
            meta.setdefault("epoch", int(epoch))
        fname = f"{self.prefix}-{int(step):010d}.pdckpt"
        path = os.path.join(self.directory, fname)
        payload = {"state": state, "meta": meta}
        with _goodput.bill("checkpoint"):
            retry(lambda: _fio.save(payload, path, protocol=self.protocol),
                  policy=self.retry_policy, site="ckpt.save")
        entries = [e for e in self.manifest() if e.get("file") != fname]
        entries.append({"file": fname, "step": int(step), "epoch": epoch,
                        "bytes": os.path.getsize(path), "meta": meta})
        entries.sort(key=lambda e: e.get("step", 0))
        self._write_manifest(entries)
        self._rotate()
        return path

    def _write_manifest(self, entries: List[dict]):
        with _fio.atomic_file(self._manifest_path()) as tmp:
            with open(tmp, "w") as f:
                json.dump(entries, f, indent=1)
                f.flush()
                os.fsync(f.fileno())

    def _rotate(self):
        doomed = self.checkpoints()[self.keep_n:]
        for path in doomed:
            try:
                os.unlink(path)
                _m_rotated.inc()
            except OSError:
                pass
        if doomed:
            gone = {os.path.basename(p) for p in doomed}
            self._write_manifest(
                [e for e in self.manifest() if e.get("file") not in gone])

    # ------------------------------------------------------------ restore
    def restore(self, verify: bool = True, max_step: Optional[int] = None
                ) -> Optional[Tuple[Any, Dict[str, Any]]]:
        """``(state, meta)`` from the newest checkpoint that loads clean,
        falling back past corrupt/partial ones (each skip warns and
        counts); None when nothing in the directory is loadable.

        ``max_step`` bounds the candidates to steps <= it — the
        consensus-rewind path restores the newest step completed on
        EVERY rank, so a rank that saved further ahead must skip its
        extra checkpoints (not a fallback: they aren't damaged, they
        are unilateral)."""
        cands = self.checkpoints()
        if max_step is not None:
            kept = []
            for path in cands:
                m = self._pat.match(os.path.basename(path))
                if m and int(m.group(1)) <= int(max_step):
                    kept.append(path)
            cands = kept
        for depth, path in enumerate(cands):
            try:
                with _goodput.bill("checkpoint"):
                    payload = _fio.load(path, verify=verify)
            except (_fio.CheckpointCorruptError, OSError, EOFError,
                    ValueError, KeyError) as e:
                warnings.warn(
                    f"CheckpointManager: skipping unloadable checkpoint "
                    f"{path!r}: {e}")
                continue
            if not isinstance(payload, dict) or "state" not in payload:
                warnings.warn(
                    f"CheckpointManager: {path!r} is not a manager "
                    f"checkpoint (no 'state' key); skipping")
                continue
            self.last_fallback_depth = depth
            _m_fallback_depth.set(depth)
            if depth:
                _m_fallback_total.inc()
            return payload["state"], dict(payload.get("meta") or {})
        self.last_fallback_depth = None
        return None


# ------------------------------------------------------- train-state glue
def capture_train_state(network=None, optimizer=None, scaler=None) -> dict:
    """Standard train-state payload: model + optimizer + GradScaler
    state_dicts (whichever are provided)."""
    state: Dict[str, Any] = {}
    if network is not None:
        state["model"] = network.state_dict()
    if optimizer is not None and hasattr(optimizer, "state_dict"):
        state["optimizer"] = optimizer.state_dict()
    if scaler is not None and hasattr(scaler, "state_dict"):
        state["scaler"] = scaler.state_dict()
    return state


def restore_train_state(state: dict, network=None, optimizer=None,
                        scaler=None):
    """Inverse of :func:`capture_train_state` (missing pieces are
    skipped, so a checkpoint saved without a scaler restores into a run
    that has one)."""
    if network is not None and state.get("model") is not None:
        network.set_state_dict(state["model"])
    if optimizer is not None and state.get("optimizer") is not None and \
            hasattr(optimizer, "set_state_dict"):
        optimizer.set_state_dict(state["optimizer"])
    if scaler is not None and state.get("scaler") is not None and \
            hasattr(scaler, "load_state_dict"):
        scaler.load_state_dict(state["scaler"])


def auto_resume(manager: CheckpointManager, network=None, optimizer=None,
                scaler=None, verify: bool = True,
                max_step: Optional[int] = None) -> Optional[dict]:
    """Restore the newest verifiable train state into the given pieces;
    returns its meta (``step``/``epoch``/...) for the training loop to
    fast-forward its counters, or None when there is nothing to resume
    from.  ``max_step`` bounds the restore to the cross-rank consensus
    step (``fault.supervisor.consensus_resume`` computes and passes it)."""
    out = manager.restore(verify=verify, max_step=max_step)
    if out is None:
        return None
    state, meta = out
    with _goodput.bill("checkpoint"):
        restore_train_state(state, network=network, optimizer=optimizer,
                            scaler=scaler)
    if meta.get("step") is not None:
        # the steps between this checkpoint and where the crashed run
        # had progressed will be recomputed — the ledger bills them as
        # restart-rewind badput (prior progress from its own account,
        # or the previous process's PADDLE_TPU_GOODPUT exit dump)
        _goodput.ledger().note_resume(int(meta["step"]))
    return meta
