"""paddle_tpu.fault — the reliability layer for training.

Three pieces, built to be provable:

- **Atomic + verified checkpoints** — ``framework.io`` saves via
  temp-file → fsync → rename with a checksummed v2 footer;
  :class:`CheckpointManager` adds rotation (``keep_n``), a manifest of
  completed saves, and ``restore()`` that falls back past a corrupt or
  partial checkpoint to the last verifiable one.
- **Retry/backoff** — :func:`retry` with exponential backoff, jitter and
  a deadline, used by checkpoint I/O and the host-side object
  collectives; exhaustion re-raises the original error.
- **Deterministic fault injection** — :mod:`.inject` names failure
  points (``io.write_truncate_after_bytes``, ``io.rename_fail``,
  ``collective.timeout``, ``grads.nan_at_step``, ``rank.crash_at_step``,
  ``rank.hang_at_step``, ``heartbeat.lease_lost``) that production code
  guards at near-zero cost and tests arm to prove every recovery path
  end-to-end.
- **Fleet supervisor** — :mod:`.supervisor`: the collective-timeout
  abort plane, lease-based rank-failure detection, cross-rank consensus
  rewind and sentinel remediation (the half that *acts* on the
  observability layer's diagnosis).

``CheckpointManager`` and the train-state helpers resolve lazily because
they sit above ``framework.io``, which itself guards its writes with
:mod:`.inject` (the package must be importable from below).
"""
from __future__ import annotations

import importlib

from . import inject
from .inject import InjectedFault
from .retry import RetryPolicy, retry

__all__ = ["inject", "InjectedFault", "RetryPolicy", "retry",
           "CheckpointManager", "auto_resume", "capture_train_state",
           "restore_train_state", "supervisor"]

_LAZY = {"CheckpointManager", "auto_resume", "capture_train_state",
         "restore_train_state"}


def __getattr__(name):
    if name in _LAZY:
        mod = importlib.import_module(".checkpoint_manager", __name__)
        for n in _LAZY:
            globals()[n] = getattr(mod, n)
        return globals()[name]
    if name == "supervisor":
        # lazy for the same reason as the checkpoint pieces: the
        # supervisor sits above observability + launch, which sit above
        # framework.io, which imports .inject from below
        mod = importlib.import_module(".supervisor", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(
        f"module 'paddle_tpu.fault' has no attribute {name!r}")
