"""Retry with exponential backoff + jitter + deadline.

Used by checkpoint I/O and the host-side object collectives: transient
filesystem and peer failures (NFS hiccup, preempted host, stuck gRPC
channel) are retried on a bounded schedule; a *persistent* failure
surfaces the ORIGINAL exception — never a wrapper — so callers and tests
see the real error class (the Megatron-LM/PaLM practice of bounded
recovery, then fail loudly).

The sleep and clock are injectable seams (``sleep=``/``clock=``) so tier-1
tests verify the exact backoff schedule without a single real sleep, and
jitter comes from an explicit ``random.Random`` so the schedule is
deterministic under test.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

from ..observability import metrics as _metrics

__all__ = ["RetryPolicy", "retry"]

_m_retries = _metrics.counter(
    "paddle_tpu_fault_retries_total",
    "Retried attempts per call site (checkpoint I/O, object collectives).",
    labelnames=("site",))


class RetryPolicy:
    """Backoff schedule: delay(k) = min(base * multiplier**k, max_delay),
    scaled by up to ±``jitter`` fraction; at most ``max_attempts`` total
    attempts and (optionally) a wall-clock ``deadline`` in seconds across
    the whole call."""

    __slots__ = ("max_attempts", "base_delay", "multiplier", "max_delay",
                 "jitter", "deadline", "retry_on")

    def __init__(self, max_attempts: int = 3, base_delay: float = 0.05,
                 multiplier: float = 2.0, max_delay: float = 2.0,
                 jitter: float = 0.1, deadline: Optional[float] = None,
                 retry_on: Tuple[Type[BaseException], ...] = (
                     OSError, TimeoutError)):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.multiplier = float(multiplier)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.deadline = deadline
        self.retry_on = tuple(retry_on)

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Backoff before retry number ``attempt`` (0-based)."""
        d = min(self.base_delay * self.multiplier ** attempt, self.max_delay)
        if self.jitter:
            d *= 1.0 + rng.uniform(-self.jitter, self.jitter)
        return max(d, 0.0)


def retry(fn: Callable, policy: Optional[RetryPolicy] = None,
          site: str = "", sleep: Optional[Callable[[float], None]] = None,
          clock: Optional[Callable[[], float]] = None,
          rng: Optional[random.Random] = None):
    """Call ``fn()``; on an exception in ``policy.retry_on``, back off and
    retry up to the attempt/deadline budget, then re-raise the original.

    Each retried attempt increments ``paddle_tpu_fault_retries_total``
    (label: ``site``) so persistent flakiness is visible on dashboards
    long before it becomes an outage.
    """
    policy = policy or RetryPolicy()
    sleep = time.sleep if sleep is None else sleep
    clock = time.monotonic if clock is None else clock
    rng = random.Random(0) if rng is None else rng
    site = site or getattr(fn, "__name__", "fn")
    start = clock()
    attempt = 0
    while True:
        try:
            return fn()
        except policy.retry_on:
            attempt += 1
            if attempt >= policy.max_attempts:
                raise
            d = policy.delay(attempt - 1, rng)
            if policy.deadline is not None and \
                    clock() - start + d > policy.deadline:
                raise
            _m_retries.inc(site=site)
            sleep(d)
