"""Minimal ONNX protobuf writer/reader — no onnx package dependency.

The image ships neither ``onnx`` nor ``protoc``-compiled bindings for it,
so this module encodes/decodes the (stable) ONNX wire format directly:
ModelProto / GraphProto / NodeProto / AttributeProto / TensorProto /
ValueInfoProto with the field numbers from onnx/onnx.proto3. Only the
subset the exporter emits is supported — which is exactly what the
bundled numpy runtime (onnx/runtime.py) and external onnxruntime need.

Reference surface: python/paddle/onnx/export.py (delegates to
paddle2onnx); here the encoder is native.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# TensorProto.DataType
FLOAT = 1
UINT8 = 2
INT8 = 3
INT32 = 6
INT64 = 7
BOOL = 9
DOUBLE = 11

NP2ONNX = {np.dtype(np.float32): FLOAT, np.dtype(np.int64): INT64,
           np.dtype(np.int32): INT32, np.dtype(np.bool_): BOOL,
           np.dtype(np.float64): DOUBLE, np.dtype(np.uint8): UINT8,
           np.dtype(np.int8): INT8}
ONNX2NP = {v: k for k, v in NP2ONNX.items()}

# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR = 1, 2, 3, 4
A_FLOATS, A_INTS, A_STRINGS = 6, 7, 8


# ---------------------------------------------------------------- writer
def _varint(n: int) -> bytes:
    n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _f_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(int(value))


def _f_bytes(field: int, value: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(value)) + value


def _f_str(field: int, value: str) -> bytes:
    return _f_bytes(field, value.encode())


def _f_float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", float(value))


def _f_packed_varints(field: int, values) -> bytes:
    body = b"".join(_varint(int(v)) for v in values)
    return _f_bytes(field, body)


def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    dt = NP2ONNX[arr.dtype]
    msg = _f_packed_varints(1, arr.shape)            # dims
    msg += _f_varint(2, dt)                          # data_type
    msg += _f_str(8, name)                           # name
    msg += _f_bytes(9, arr.tobytes())                # raw_data
    return msg


def attribute(name: str, value) -> bytes:
    msg = _f_str(1, name)
    if isinstance(value, float):
        msg += _f_float(2, value) + _f_varint(20, A_FLOAT)
    elif isinstance(value, bool) or isinstance(value, (int, np.integer)):
        msg += _f_varint(3, int(value)) + _f_varint(20, A_INT)
    elif isinstance(value, str):
        msg += _f_bytes(4, value.encode()) + _f_varint(20, A_STRING)
    elif isinstance(value, np.ndarray):
        msg += _f_bytes(5, tensor_proto(name + "_t", value))
        msg += _f_varint(20, A_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            body = b"".join(_tag(7, 5) + struct.pack("<f", v)
                            for v in value)
            msg += body + _f_varint(20, A_FLOATS)
        else:
            msg += _f_packed_varints(8, value) + _f_varint(20, A_INTS)
    else:
        raise TypeError(f"unsupported attribute {name}={value!r}")
    return msg


def node(op_type: str, inputs: List[str], outputs: List[str],
         name: str = "", attrs: Optional[Dict[str, Any]] = None) -> bytes:
    msg = b"".join(_f_str(1, i) for i in inputs)
    msg += b"".join(_f_str(2, o) for o in outputs)
    if name:
        msg += _f_str(3, name)
    msg += _f_str(4, op_type)
    for k, v in (attrs or {}).items():
        msg += _f_bytes(5, attribute(k, v))
    return msg


def value_info(name: str, shape: Tuple[int, ...], elem_type: int) -> bytes:
    dims = b""
    for d in shape:
        if d is None or d < 0:
            dims += _f_bytes(1, _f_str(2, "N"))      # dim_param
        else:
            dims += _f_bytes(1, _f_varint(1, d))     # dim_value
    tens = _f_varint(1, elem_type) + _f_bytes(2, dims)
    return _f_str(1, name) + _f_bytes(2, _f_bytes(1, tens))


def graph(nodes: List[bytes], name: str, initializers: List[bytes],
          inputs: List[bytes], outputs: List[bytes]) -> bytes:
    msg = b"".join(_f_bytes(1, n) for n in nodes)
    msg += _f_str(2, name)
    msg += b"".join(_f_bytes(5, t) for t in initializers)
    msg += b"".join(_f_bytes(11, v) for v in inputs)
    msg += b"".join(_f_bytes(12, v) for v in outputs)
    return msg


def model(graph_bytes: bytes, opset: int = 13,
          producer: str = "paddle_tpu") -> bytes:
    msg = _f_varint(1, 8)                            # ir_version
    msg += _f_str(2, producer)
    msg += _f_bytes(7, graph_bytes)
    msg += _f_bytes(8, _f_str(1, "") + _f_varint(2, opset))  # opset_import
    return msg


# ---------------------------------------------------------------- reader
def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one message."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            v = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            v = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, v


def _parse_packed_varints(data: bytes) -> List[int]:
    out, pos = [], 0
    while pos < len(data):
        v, pos = _read_varint(data, pos)
        out.append(v)
    return out


def _signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def parse_tensor(buf: bytes) -> Tuple[str, np.ndarray]:
    dims: List[int] = []
    dtype = FLOAT
    name = ""
    raw = b""
    floats: List[float] = []
    ints: List[int] = []
    for field, wire, v in _fields(buf):
        if field == 1:
            dims += _parse_packed_varints(v) if wire == 2 else [v]
        elif field == 2:
            dtype = v
        elif field == 8:
            name = v.decode()
        elif field == 9:
            raw = v
        elif field == 4:
            floats += (list(np.frombuffer(v, "<f4")) if wire == 2
                       else [struct.unpack("<f", v)[0]])
        elif field == 7:
            ints += _parse_packed_varints(v) if wire == 2 else [v]
    np_dt = ONNX2NP[dtype]
    if raw:
        arr = np.frombuffer(raw, np_dt).reshape(dims)
    elif floats:
        arr = np.asarray(floats, np_dt).reshape(dims)
    else:
        arr = np.asarray([_signed(i) for i in ints], np_dt).reshape(dims)
    return name, arr


def parse_attribute(buf: bytes) -> Tuple[str, Any]:
    name, value, atype = "", None, None
    ints: List[int] = []
    floats: List[float] = []
    for field, wire, v in _fields(buf):
        if field == 1:
            name = v.decode()
        elif field == 2:
            value = struct.unpack("<f", v)[0]
        elif field == 3:
            ints.append(_signed(v))
        elif field == 4:
            value = v.decode()
        elif field == 5:
            value = parse_tensor(v)[1]
        elif field == 7:
            floats += (list(np.frombuffer(v, "<f4")) if wire == 2
                       else [struct.unpack("<f", v)[0]])
        elif field == 8:
            ints += ([_signed(i) for i in _parse_packed_varints(v)]
                     if wire == 2 else [_signed(v)])
        elif field == 20:
            atype = v
    if atype == A_INT:
        return name, ints[0]
    if atype == A_INTS:
        return name, ints
    if atype == A_FLOATS:
        return name, floats
    return name, value


def parse_node(buf: bytes) -> dict:
    out = {"input": [], "output": [], "op_type": "", "name": "",
           "attrs": {}}
    for field, _w, v in _fields(buf):
        if field == 1:
            out["input"].append(v.decode())
        elif field == 2:
            out["output"].append(v.decode())
        elif field == 3:
            out["name"] = v.decode()
        elif field == 4:
            out["op_type"] = v.decode()
        elif field == 5:
            k, val = parse_attribute(v)
            out["attrs"][k] = val
    return out


def parse_value_info(buf: bytes) -> dict:
    name, shape, elem = "", [], FLOAT
    for field, _w, v in _fields(buf):
        if field == 1:
            name = v.decode()
        elif field == 2:
            for f2, _w2, v2 in _fields(v):
                if f2 == 1:  # tensor_type
                    for f3, _w3, v3 in _fields(v2):
                        if f3 == 1:
                            elem = v3
                        elif f3 == 2:
                            for f4, _w4, v4 in _fields(v3):
                                if f4 == 1:
                                    dv = None
                                    for f5, _w5, v5 in _fields(v4):
                                        if f5 == 1:
                                            dv = v5
                                    shape.append(dv)
    return {"name": name, "shape": shape, "elem_type": elem}


def parse_graph(buf: bytes) -> dict:
    g = {"nodes": [], "name": "", "initializers": {}, "inputs": [],
         "outputs": []}
    for field, _w, v in _fields(buf):
        if field == 1:
            g["nodes"].append(parse_node(v))
        elif field == 2:
            g["name"] = v.decode()
        elif field == 5:
            n, arr = parse_tensor(v)
            g["initializers"][n] = arr
        elif field == 11:
            g["inputs"].append(parse_value_info(v))
        elif field == 12:
            g["outputs"].append(parse_value_info(v))
    return g


def parse_model(buf: bytes) -> dict:
    m = {"ir_version": None, "producer": "", "opset": None, "graph": None}
    for field, _w, v in _fields(buf):
        if field == 1:
            m["ir_version"] = v
        elif field == 2:
            m["producer"] = v.decode()
        elif field == 7:
            m["graph"] = parse_graph(v)
        elif field == 8:
            for f2, _w2, v2 in _fields(v):
                if f2 == 2:
                    m["opset"] = v2
    return m
