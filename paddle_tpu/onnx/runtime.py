"""Pure-numpy evaluator for the exported ONNX op subset.

Role: the image ships no onnxruntime, so exported models are verified by
executing the .onnx file with THIS interpreter and comparing logits
against the live model (tests/test_onnx_export.py); when onnxruntime is
available the same files run there (op semantics follow the ONNX spec).
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from . import proto


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


def _pool_view(x: np.ndarray, kh: int, kw: int, sh: int, sw: int):
    """(N, C, OH, OW, kh, kw) sliding-window view of NCHW input."""
    N, C, H, W = x.shape
    oh = (H - kh) // sh + 1
    ow = (W - kw) // sw + 1
    s = x.strides
    return np.lib.stride_tricks.as_strided(
        x, (N, C, oh, ow, kh, kw),
        (s[0], s[1], s[2] * sh, s[3] * sw, s[2], s[3]),
        writeable=False)


def _pad_nchw(x, pads, value=0.0):
    # ONNX pads: [h_begin, w_begin, h_end, w_end]
    hb, wb, he, we = pads
    return np.pad(x, ((0, 0), (0, 0), (hb, he), (wb, we)),
                  constant_values=value)


def _auto_pads(auto_pad, in_hw, k_hw, strides):
    """SAME_UPPER/SAME_LOWER pads per the ONNX spec."""
    pads = [0, 0, 0, 0]
    for i in (0, 1):
        out = -(-in_hw[i] // strides[i])
        total = max((out - 1) * strides[i] + k_hw[i] - in_hw[i], 0)
        lo = total // 2 if auto_pad == "SAME_UPPER" else total - total // 2
        pads[i], pads[i + 2] = lo, total - lo
    return pads


def _resolve_pads(attrs, in_hw, k_hw, strides):
    auto = attrs.get("auto_pad", "NOTSET")
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        return _auto_pads(auto, in_hw, k_hw, strides)
    if auto == "VALID":
        return [0, 0, 0, 0]
    return attrs.get("pads", [0, 0, 0, 0])


def _conv(x, w, b, attrs):
    group = int(attrs.get("group", 1))
    strides = attrs.get("strides", [1, 1])
    dil = attrs.get("dilations", [1, 1])
    eff_k = [(w.shape[2] - 1) * dil[0] + 1, (w.shape[3] - 1) * dil[1] + 1]
    pads = _resolve_pads(attrs, x.shape[2:], eff_k, strides)
    x = _pad_nchw(x, pads)
    if list(dil) != [1, 1]:
        # dilate the kernel explicitly
        kh, kw = w.shape[2], w.shape[3]
        wk = np.zeros(w.shape[:2] + ((kh - 1) * dil[0] + 1,
                                     (kw - 1) * dil[1] + 1), w.dtype)
        wk[:, :, ::dil[0], ::dil[1]] = w
        w = wk
    N, C, H, W = x.shape
    O, Cg, kh, kw = w.shape
    og = O // group
    outs = []
    for g in range(group):
        xg = x[:, g * Cg:(g + 1) * Cg]
        wg = w[g * og:(g + 1) * og]
        view = _pool_view(xg, kh, kw, strides[0], strides[1])
        # (N, C, OH, OW, kh, kw) x (og, C, kh, kw) -> (N, og, OH, OW)
        outs.append(np.einsum("nchwij,ocij->nohw", view, wg,
                              optimize=True))
    y = np.concatenate(outs, axis=1)
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1)
    return y.astype(np.float32)


def _maxpool(x, attrs):
    kh, kw = attrs["kernel_shape"]
    sh, sw = attrs.get("strides", [kh, kw])
    pads = _resolve_pads(attrs, x.shape[2:], [kh, kw], [sh, sw])
    if attrs.get("ceil_mode", 0):
        N, C, H, W = x.shape
        eh = -(-(H + pads[0] + pads[2] - kh) // sh) * sh + kh
        ew = -(-(W + pads[1] + pads[3] - kw) // sw) * sw + kw
        pads = [pads[0], pads[1],
                max(pads[2], eh - H - pads[0]),
                max(pads[3], ew - W - pads[1])]
    xp = _pad_nchw(x, pads, value=-np.inf)
    return _pool_view(xp, kh, kw, sh, sw).max(axis=(4, 5))


def _avgpool(x, attrs):
    kh, kw = attrs["kernel_shape"]
    sh, sw = attrs.get("strides", [kh, kw])
    pads = _resolve_pads(attrs, x.shape[2:], [kh, kw], [sh, sw])
    include_pad = bool(attrs.get("count_include_pad", 0))
    xp = _pad_nchw(x, pads)
    s = _pool_view(xp, kh, kw, sh, sw).sum(axis=(4, 5))
    if include_pad:
        return (s / (kh * kw)).astype(x.dtype)
    ones = _pad_nchw(np.ones_like(x), pads)
    cnt = _pool_view(ones, kh, kw, sh, sw).sum(axis=(4, 5))
    return (s / cnt).astype(x.dtype)


def _gemm(a, b, c, attrs):
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    if attrs.get("transA", 0):
        a = a.T
    if attrs.get("transB", 0):
        b = b.T
    y = alpha * (a @ b)
    if c is not None:
        y = y + beta * c
    return y


def _reshape(x, shape):
    shape = [int(s) for s in shape]
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return x.reshape(shape)


def _softmax(x, axis):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def run(model_bytes: bytes, feeds: Dict[str, np.ndarray]
        ) -> List[np.ndarray]:
    """Execute a serialized ModelProto on numpy feeds; returns the graph
    outputs in declared order."""
    g = proto.parse_model(model_bytes)["graph"]
    env: Dict[str, np.ndarray] = dict(g["initializers"])
    env.update({k: np.asarray(v) for k, v in feeds.items()})

    def get(name):
        return env[name] if name else None

    for nd in g["nodes"]:
        op = nd["op_type"]
        ins = [get(n) for n in nd["input"]]
        attrs = nd["attrs"]
        if op == "Conv":
            out = _conv(ins[0], ins[1],
                        ins[2] if len(ins) > 2 else None, attrs)
        elif op == "Relu":
            out = np.maximum(ins[0], 0)
        elif op == "MaxPool":
            out = _maxpool(ins[0], attrs)
        elif op == "AveragePool":
            out = _avgpool(ins[0], attrs)
        elif op == "GlobalAveragePool":
            out = ins[0].mean(axis=(2, 3), keepdims=True)
        elif op == "BatchNormalization":
            x, scale, bias, mean, var = ins[:5]
            eps = attrs.get("epsilon", 1e-5)
            shp = (1, -1) + (1,) * (x.ndim - 2)
            out = ((x - mean.reshape(shp))
                   / np.sqrt(var.reshape(shp) + eps)
                   * scale.reshape(shp) + bias.reshape(shp))
            out = out.astype(x.dtype)
        elif op == "Gemm":
            out = _gemm(ins[0], ins[1],
                        ins[2] if len(ins) > 2 else None, attrs)
        elif op == "MatMul":
            out = ins[0] @ ins[1]
        elif op == "Add":
            out = ins[0] + ins[1]
        elif op == "Sub":
            out = ins[0] - ins[1]
        elif op == "Mul":
            out = ins[0] * ins[1]
        elif op == "Div":
            out = ins[0] / ins[1]
        elif op == "Reshape":
            out = _reshape(ins[0], ins[1])
        elif op == "Flatten":
            ax = attrs.get("axis", 1)
            out = ins[0].reshape(int(np.prod(ins[0].shape[:ax])), -1)
        elif op == "Softmax":
            out = _softmax(ins[0], attrs.get("axis", -1))
        elif op == "Tanh":
            out = np.tanh(ins[0])
        elif op == "Sigmoid":
            out = 1.0 / (1.0 + np.exp(-ins[0]))
        elif op == "Identity":
            out = ins[0]
        else:
            raise NotImplementedError(f"runtime op {op}")
        env[nd["output"][0]] = out
    return [env[o["name"]] for o in g["outputs"]]
