"""paddle.onnx — export surface (reference: python/paddle/onnx/export.py
delegating to the external paddle2onnx package). The TPU-native deployment
artifact is serialized StableHLO (paddle_tpu.jit.save / paddle_tpu.
inference); ONNX conversion would require the external converter, which
has no TPU-side analog — export() points users at the supported path."""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export is not supported in the TPU-native stack (the "
        "reference delegates to the external paddle2onnx CUDA toolchain). "
        "Use paddle_tpu.jit.save(layer, path, input_spec=...) to produce "
        "a portable StableHLO program and serve it with "
        "paddle_tpu.inference.create_predictor")


__all__ = ["export"]
