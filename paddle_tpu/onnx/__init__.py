"""paddle.onnx — native ONNX export.

Reference surface: python/paddle/onnx/export.py (``paddle.onnx.export``
delegates to paddle2onnx). TPU-native implementation: the model runs once
under a dispatch export hook (core/dispatch.register_export_hook) that
records each op with its SEMANTIC parameters; the recorded graph is
mapped to ONNX ops and serialized by the bundled protobuf writer
(onnx/proto.py — the image ships no onnx package). Exported files
execute on onnxruntime; the bundled numpy evaluator (onnx/runtime.py)
verifies them hermetically in CI.

Supported subset: the convnet ops (Conv/BN/Relu/Pool/Gemm/Reshape/
Flatten/Add/.../Softmax) — LeNet and the ResNet family export and verify
end to end. Unsupported ops raise ``NotImplementedError`` naming the op.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor
from . import proto, runtime

__all__ = ["export", "run"]


def _sanitize(name: str) -> str:
    return re.sub(r"[^0-9a-zA-Z_./]", "_", name)


class _Trace:
    def __init__(self):
        self.records: List[tuple] = []
        self.keepalive: List[Any] = []  # pin Tensor ids during the trace

    def hook(self, op_name, tensor_inputs, out_tensors, attrs):
        self.records.append((op_name, [id(t) for t in tensor_inputs],
                             [np.asarray(t._data) for t in tensor_inputs],  # tpulint: disable=TPU104 — export-by-design: the ONNX trace snapshots host values for constant folding
                             [id(t) for t in out_tensors],
                             [tuple(t.shape) for t in out_tensors],
                             dict(attrs)))
        self.keepalive.extend(tensor_inputs)
        self.keepalive.extend(out_tensors)


def _onnx_pads(padding, op: str):
    """(lo,hi)-pairs / 'SAME' / 'VALID' -> (pads list, auto_pad)."""
    if isinstance(padding, str):
        if padding.upper() == "VALID":
            return [0, 0, 0, 0], None
        return None, "SAME_UPPER"
    pairs = [tuple(p) for p in padding]
    if len(pairs) != 2:
        raise NotImplementedError(f"{op}: only 2-D spatial export")
    return [pairs[0][0], pairs[1][0], pairs[0][1], pairs[1][1]], None


class _Builder:
    def __init__(self, name_of: Dict[int, str],
                 params: Dict[int, np.ndarray]):
        self.name_of = name_of          # tensor id -> value name
        self.params = params            # tensor id -> ndarray (weights)
        self.nodes: List[bytes] = []
        self.initializers: Dict[str, np.ndarray] = {}
        self.counter = 0

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def init_const(self, name: str, arr: np.ndarray) -> str:
        self.initializers[name] = np.asarray(arr)
        return name

    def in_name(self, tid: int, value: np.ndarray) -> str:
        nm = self.name_of.get(tid)
        if nm is None:
            # a tensor from outside the traced graph: bake as initializer
            nm = self.fresh("const")
            self.name_of[tid] = nm
            self.initializers[nm] = np.asarray(value)
        elif tid in self.params and nm not in self.initializers:
            self.initializers[nm] = self.params[tid]
        return nm

    def out_name(self, tid: int) -> str:
        nm = self.name_of.get(tid)
        if nm is None:
            nm = self.name_of[tid] = self.fresh()
        return nm

    def emit(self, op_type, ins, outs, attrs=None):
        self.nodes.append(proto.node(
            op_type, ins, outs, name=self.fresh(op_type), attrs=attrs))


_ELTWISE = {"add": "Add", "subtract": "Sub", "sub": "Sub",
            "multiply": "Mul", "mul": "Mul", "divide": "Div",
            "div": "Div"}
_UNARY = {"relu": "Relu", "tanh": "Tanh", "sigmoid": "Sigmoid"}


def _map_record(b: _Builder, op, in_ids, in_vals, out_ids, out_shapes,
                attrs):
    ins = [b.in_name(t, v) for t, v in zip(in_ids, in_vals)]
    outs = [b.out_name(t) for t in out_ids]

    if op in _UNARY:
        b.emit(_UNARY[op], ins, outs)
    elif op in _ELTWISE:
        b.emit(_ELTWISE[op], ins, outs)
    elif op == "conv2d":
        if attrs.get("channel_last"):
            raise NotImplementedError("conv2d NHWC export")
        pads, auto = _onnx_pads(attrs["padding"], op)
        a: Dict[str, Any] = {"strides": list(attrs["stride"]),
                             "dilations": list(attrs["dilation"]),
                             "group": int(attrs["groups"])}
        if auto:
            a["auto_pad"] = auto
        else:
            a["pads"] = pads
        b.emit("Conv", ins, outs, a)
    elif op in ("max_pool2d", "avg_pool2d"):
        if attrs.get("channel_last"):
            raise NotImplementedError(f"{op} NHWC export")
        pads, auto = _onnx_pads(attrs["padding"], op)
        a = {"kernel_shape": list(attrs["kernel_size"]),
             "strides": list(attrs["stride"]),
             "ceil_mode": int(bool(attrs.get("ceil_mode")))}
        if auto:
            a["auto_pad"] = auto
        else:
            a["pads"] = pads
        if op == "avg_pool2d":
            a["count_include_pad"] = 0 if attrs.get("exclusive", True) \
                else 1
            b.emit("AveragePool", ins, outs, a)
        else:
            b.emit("MaxPool", ins, outs, a)
    elif op == "adaptive_avg_pool2d":
        osz = attrs.get("output_size")
        osz = (osz, osz) if isinstance(osz, int) else tuple(osz)
        if tuple(osz) != (1, 1):
            raise NotImplementedError(
                "adaptive_avg_pool2d export needs output_size 1")
        b.emit("GlobalAveragePool", ins, outs)
    elif op == "batch_norm":
        x_name = ins[0]
        C = attrs["mean"].shape[0]
        widx = 1
        scale = (ins[widx] if attrs["has_w"]
                 else b.init_const(b.fresh("bn_scale"),
                                   np.ones(C, np.float32)))
        widx += 1 if attrs["has_w"] else 0
        bias = (ins[widx] if attrs["has_b"]
                else b.init_const(b.fresh("bn_bias"),
                                  np.zeros(C, np.float32)))
        mean = b.init_const(b.fresh("bn_mean"), attrs["mean"])
        var = b.init_const(b.fresh("bn_var"), attrs["var"])
        b.emit("BatchNormalization", [x_name, scale, bias, mean, var],
               outs, {"epsilon": float(attrs["epsilon"])})
    elif op == "linear":
        if len(in_vals[0].shape) == 2:
            b.emit("Gemm", ins, outs)
        else:
            mm = b.fresh("matmul")
            b.emit("MatMul", ins[:2], [mm])
            if len(ins) > 2:
                b.emit("Add", [mm, ins[2]], outs)
            else:
                b.emit("Identity", [mm], outs)
    elif op == "matmul":
        b.emit("MatMul", ins[:2], outs)
    elif op == "reshape":
        out_shape = [int(s) for s in out_shapes[0]]
        if tuple(in_vals[0].shape[:1]) == tuple(out_shape[:1]):
            # batch dim preserved: emit 0 (copy) so the graph serves any
            # batch size; otherwise the traced shape is baked in (the
            # export is batch-specialized for that reshape)
            shape = [0] + out_shape[1:]
        else:
            shape = out_shape
        shp = b.init_const(b.fresh("shape"),
                           np.asarray(shape, np.int64))
        b.emit("Reshape", [ins[0], shp], outs)
    elif op == "flatten":
        s_ax = int(attrs.get("start_axis", 1))
        e_ax = int(attrs.get("stop_axis", len(in_vals[0].shape) - 1))
        if s_ax >= 1 and e_ax == len(in_vals[0].shape) - 1:
            b.emit("Flatten", ins, outs, {"axis": s_ax})
        else:
            # partial flatten: exact Reshape to the traced output shape
            out_shape = [int(s) for s in out_shapes[0]]
            shape = ([0] + out_shape[1:]
                     if s_ax >= 1 and tuple(in_vals[0].shape[:1])
                     == tuple(out_shape[:1]) else out_shape)
            shp = b.init_const(b.fresh("shape"),
                               np.asarray(shape, np.int64))
            b.emit("Reshape", [ins[0], shp], outs)
    elif op == "softmax":
        ax = int(attrs.get("axis", -1))
        b.emit("Softmax", ins, outs, {"axis": ax})
    elif op == "dropout":
        b.emit("Identity", ins, outs)
    else:
        raise NotImplementedError(
            f"ONNX export does not support op {op!r} yet "
            f"(supported: convnet subset — see paddle_tpu/onnx)")


def _example_inputs(input_spec):
    import jax.numpy as jnp
    out = []
    for spec in input_spec:
        if isinstance(spec, Tensor):
            out.append(spec)
            continue
        if isinstance(spec, np.ndarray):
            out.append(Tensor(jnp.asarray(spec)))
            continue
        shape = tuple(1 if (s is None or s == -1) else int(s)
                      for s in spec.shape)
        dtype = np.dtype(str(getattr(spec, "dtype", "float32"))
                         or "float32")
        out.append(Tensor(jnp.zeros(shape, dtype)))
    return out


def export(layer, path: str, input_spec=None, opset_version: int = 13,
           **configs) -> str:
    """Export ``layer`` to ``path + '.onnx'`` (reference
    paddle.onnx.export contract). Returns the written file path."""
    if input_spec is None:
        raise ValueError("paddle.onnx.export requires input_spec")
    inputs = _example_inputs(list(input_spec))

    params: Dict[int, np.ndarray] = {}
    name_of: Dict[int, str] = {}
    if hasattr(layer, "named_parameters"):
        for n, p in layer.named_parameters():
            name_of[id(p)] = _sanitize(n)
            params[id(p)] = np.asarray(p._data)  # tpulint: disable=TPU104 — export-by-design: initializers bake host copies into the ONNX file
    if hasattr(layer, "named_buffers"):
        for n, p in layer.named_buffers():
            name_of[id(p)] = _sanitize(n)
            params[id(p)] = np.asarray(p._data)  # tpulint: disable=TPU104 — export-by-design: initializers bake host copies into the ONNX file
    graph_inputs = []
    for i, t in enumerate(inputs):
        name_of[id(t)] = f"x{i}"
        graph_inputs.append(proto.value_info(
            f"x{i}", (None,) + tuple(t.shape[1:]),
            proto.NP2ONNX[np.dtype(t.dtype)]))

    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    tr = _Trace()
    dispatch.register_export_hook(tr.hook)
    try:
        with dispatch.no_grad():
            result = layer(*inputs)
    finally:
        dispatch.unregister_export_hook(tr.hook)
        if was_training and hasattr(layer, "train"):
            layer.train()

    outputs = result if isinstance(result, (list, tuple)) else [result]
    out_tensors = [o for o in outputs if isinstance(o, Tensor)]

    b = _Builder(name_of, params)
    for rec in tr.records:
        _map_record(b, *rec)

    graph_outputs = []
    for i, t in enumerate(out_tensors):
        nm = b.name_of.get(id(t))
        if nm is None:
            raise RuntimeError("model output was not produced by a "
                               "traced op")
        graph_outputs.append(proto.value_info(
            nm, (None,) + tuple(t.shape[1:]),
            proto.NP2ONNX[np.dtype(t.dtype)]))

    inits = [proto.tensor_proto(n, a) for n, a in b.initializers.items()]
    g = proto.graph(b.nodes, _sanitize(type(layer).__name__ or "model"),
                    inits, graph_inputs, graph_outputs)
    blob = proto.model(g, opset=opset_version)
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(blob)
    return out_path


def run(path: str, feeds: Dict[str, np.ndarray]) -> List[np.ndarray]:
    """Execute an exported .onnx file with the bundled numpy runtime."""
    with open(path, "rb") as f:
        blob = f.read()
    return runtime.run(blob, feeds)
