"""Optimizers.

Capability parity with the reference optimizer suite (reference:
python/paddle/optimizer/optimizer.py base; adam.py, adamw.py, momentum.py,
sgd.py + fused GPU kernels paddle/phi/kernels/gpu/adam_kernel.cu).
TPU-native design: the whole step — every parameter's update — is ONE jitted
XLA program (a pytree-mapped update rule), mirroring the reference's fused
multi-tensor Adam but via compiler fusion instead of a hand-written
multi_tensor kernel. The learning rate enters as a scalar argument so LR
schedules never retrace. Master weights (multi_precision) are fp32 shadow
buffers for bf16 params, as in the reference's master-weight plumbing.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core import dtype as dtypes
from ..core.tensor import Tensor
from .lr import LRScheduler


class Optimizer:
    _state_names: List[str] = []

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        if parameters is None:
            raise ValueError(
                "parameters is required in dygraph mode "
                "(pass model.parameters())")
        self._parameter_list = list(parameters)
        self._param_groups = None
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            self._param_groups = self._parameter_list
            flat = []
            for group in self._param_groups:
                flat.extend(group["params"])
            self._parameter_list = flat
        self._learning_rate = learning_rate
        self.regularization = weight_decay
        self._weight_decay = self._coeff(weight_decay)
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: Dict[int, Dict[str, jnp.ndarray]] = {}
        self._master_weights: Dict[int, jnp.ndarray] = {}
        self._step_count = 0
        self._jit_step = jax.jit(self._tree_step)
        # HBM attribution: moments + master weights report under the
        # "optimizer_state" tag (weakly bound — telemetry must not pin a
        # dropped optimizer's state in memory)
        from ..observability.perf import memory as _perf_memory
        _perf_memory.register_object(
            "optimizer_state", self,
            lambda o: (o._accumulators, o._master_weights))

    @staticmethod
    def _coeff(wd):
        if wd is None:
            return 0.0
        if hasattr(wd, "_coeff"):       # L2Decay objects
            return float(wd._coeff)
        if hasattr(wd, "coeff"):
            return float(wd.coeff)
        return float(wd)

    # -------------------------------------------------------------------- lr
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the learning rate is a scheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler: LRScheduler):
        self._learning_rate = scheduler

    # ------------------------------------------------------------ state mgmt
    def _ensure_state(self, p: Tensor):
        pid = id(p)
        if pid in self._accumulators:
            return
        self._accumulators[pid] = self._init_state(p)
        if self._multi_precision and p.dtype in (dtypes.bfloat16,
                                                 dtypes.float16):
            self._master_weights[pid] = p._data.astype(jnp.float32)

    def _init_state(self, p: Tensor) -> Dict[str, jnp.ndarray]:
        return {name: jnp.zeros_like(self._fp32(p._data))
                for name in self._state_names}

    @staticmethod
    def _fp32(arr):
        d = np.dtype(arr.dtype)
        if d in (dtypes.bfloat16, dtypes.float16):
            return arr.astype(jnp.float32)
        return arr

    # ----------------------------------------------------------------- hooks
    def _update(self, p, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        """Pure update rule. Returns (new_param_fp32, new_state dict).
        Subclasses implement. p is fp32 (master) view; g is fp32.
        ``wd_flag`` is the per-param weight-decay multiplier (0.0 for params
        excluded by apply_decay_param_fun / exclude_from_weight_decay_fn)."""
        raise NotImplementedError

    def _wd_flag(self, p) -> float:
        """Per-param weight-decay gate; subclasses override. A param
        carrying its own ParamAttr regularizer opts out of the
        optimizer-level decay (reference priority rule)."""
        if getattr(p, "regularizer", None) is not None:
            return 0.0
        return 1.0

    def _tree_step(self, lr, step, params, grads, masters, states, lr_mults,
                   wd_flags):
        new_params, new_masters, new_states = [], [], []
        for p, g, m, st, mult, wd in zip(params, grads, masters, states,
                                         lr_mults, wd_flags):
            work = m if m is not None else self._fp32(p)
            g32 = self._fp32(g)
            new_w, new_st = self._update(work, g32, m, st, lr * mult, mult,
                                         step, wd)
            new_params.append(new_w.astype(p.dtype))
            new_masters.append(new_w if m is not None else None)
            new_states.append(new_st)
        return new_params, new_masters, new_states

    # ------------------------------------------------------------------ step
    @dispatch.no_grad()
    def step(self):
        params = [p for p in self._parameter_list
                  if (not p.stop_gradient) and p.grad is not None]
        if not params:
            self._post_step()
            return
        grads = [p.grad for p in params]
        if self._grad_clip is not None:
            clipped = self._grad_clip(list(zip(params, grads)))
            params = [p for p, g in clipped]
            grads = [g for p, g in clipped]

        for p in params:
            self._ensure_state(p)

        # ParamAttr-level regularizers take priority over the optimizer's
        # (reference regularizer.py): fold them here, per param; the
        # optimizer-level decay is gated off for those params via _wd_flag
        if any(getattr(p, "regularizer", None) is not None for p in params):
            from ..regularizer import L1Decay
            folded = []
            for p, g in zip(params, grads):
                reg = getattr(p, "regularizer", None)
                if reg is not None:
                    coeff = float(getattr(reg, "_coeff", 0.0))
                    fold = (jnp.sign(p._data) if isinstance(reg, L1Decay)
                            else p._data)
                    g = Tensor(g._data + coeff * fold.astype(g._data.dtype))
                folded.append(g)
            grads = folded

        self._step_count += 1
        lr = jnp.asarray(self.get_lr(), dtype=jnp.float32)
        step = jnp.asarray(self._step_count, dtype=jnp.int32)

        # Pipeline parallel places each stage's params on a disjoint
        # sub-mesh; one XLA program cannot span them, so group params by
        # device set and run the jitted tree-step per group (one group ==
        # one program in the common non-PP case).
        groups: Dict[object, List[int]] = {}
        for i, p in enumerate(params):
            sh = getattr(p._data, "sharding", None)
            key = frozenset(getattr(sh, "device_set", ()) or ())
            groups.setdefault(key, []).append(i)

        for idxs in groups.values():
            gp = [params[i] for i in idxs]
            gg = [grads[i] for i in idxs]
            masters = [self._master_weights.get(id(p)) for p in gp]
            states = [self._accumulators[id(p)] for p in gp]
            lr_mults = [float(getattr(p, "optimize_attr", {})
                              .get("learning_rate", 1.0)) for p in gp]
            wd_flags = [self._wd_flag(p) for p in gp]

            new_params, new_masters, new_states = self._jit_step(
                lr, step, [p._data for p in gp], [g._data for g in gg],
                masters, states, tuple(lr_mults), tuple(wd_flags))

            for p, np_, nm, ns in zip(gp, new_params, new_masters,
                                      new_states):
                p._swap_payload(np_)
                if nm is not None:
                    self._master_weights[id(p)] = nm
                self._accumulators[id(p)] = ns
        self._post_step()

    def _post_step(self):
        pass

    minimize = None  # set below

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    # ------------------------------------------------------------- save/load
    def _state_to_checkpoint(self, name, v, p):
        """Storage form -> checkpoint form (f32; quantized moments decode
        so checkpoints stay portable across moment_dtype settings)."""
        return v

    def _state_from_checkpoint(self, name, arr, p):
        return arr

    def state_dict(self):
        sd = {}
        for i, p in enumerate(self._parameter_list):
            st = self._accumulators.get(id(p))
            if st is None:
                continue
            for name, v in st.items():
                sd[f"{p.name}_{name}"] = Tensor(
                    self._state_to_checkpoint(name, v, p))
            mw = self._master_weights.get(id(p))
            if mw is not None:
                sd[f"{p.name}_master"] = Tensor(mw)
        sd["@step_count"] = self._step_count
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("@step_count", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate,
                                                       LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for p in self._parameter_list:
            st = {}
            for name in self._state_names:
                key = f"{p.name}_{name}"
                if key in state_dict:
                    v = state_dict[key]
                    arr = v._data if isinstance(v, Tensor) else jnp.asarray(v)
                    st[name] = self._state_from_checkpoint(name, arr, p)
            if st:
                self._accumulators[id(p)] = st
            mkey = f"{p.name}_master"
            if mkey in state_dict:
                v = state_dict[mkey]
                self._master_weights[id(p)] = (
                    v._data if isinstance(v, Tensor) else jnp.asarray(v))

    def _apply_decay(self, w, g, wd_flag=1.0):
        """Optimizer-level regularization folded into the gradient
        (reference regularizer.py: L2Decay → g + coeff·w, L1Decay → g +
        coeff·sign(w)). ``wd_flag`` is the per-param gate — 0.0 for
        params carrying their own ParamAttr regularizer (which takes
        priority and is folded in ``step``) or excluded by
        apply_decay_param_fun."""
        if self._weight_decay:
            from ..regularizer import L1Decay
            if isinstance(self.regularization, L1Decay):
                return g + wd_flag * self._weight_decay * jnp.sign(w)
            return g + wd_flag * self._weight_decay * w
        return g


def _minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
    loss.backward()
    self.step()
    self.clear_grad()
    return None, None


Optimizer.minimize = _minimize


class SGD(Optimizer):
    def _update(self, w, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        g = self._apply_decay(w, g, wd_flag)
        return w - lr * g, state


class Momentum(Optimizer):
    _state_names = ["velocity"]

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update(self, w, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        g = self._apply_decay(w, g, wd_flag)
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            new_w = w - lr * (g + self._momentum * v)
        else:
            new_w = w - lr * v
        return new_w, {"velocity": v}


#: block length for int8 blockwise moment quantization (one f32 absmax
#: scale per block; the bitsandbytes 8-bit-Adam layout, compiled by XLA)
_MOMENT_BLOCK = 256


def _moment_encode(x, dtype, nonneg=False):
    """f32 moment -> storage form. int8: flatten, pad to blocks of
    ``_MOMENT_BLOCK``, absmax-scale each block to int8. Non-negative
    moments (Adam's v) quantize in sqrt space — squaring back on decode
    preserves the small-variance entries that set the effective lr."""
    if dtype is None:
        return x
    if dtype == "bfloat16":
        return x.astype(jnp.bfloat16)
    if nonneg:
        x = jnp.sqrt(jnp.maximum(x, 0.0))
    flat = x.reshape(-1)
    pad = (-flat.size) % _MOMENT_BLOCK
    fp = jnp.pad(flat, (0, pad)).reshape(-1, _MOMENT_BLOCK)
    scale = jnp.max(jnp.abs(fp), axis=1, keepdims=True) / 127.0
    q = jnp.round(fp / jnp.maximum(scale, 1e-30)) \
        .clip(-127, 127).astype(jnp.int8)
    return {"q": q, "s": scale.astype(jnp.float32)}


def _moment_decode(st, shape, dtype, nonneg=False):
    """Storage form -> f32 moment of ``shape``."""
    if dtype is None:
        return st
    if dtype == "bfloat16":
        return st.astype(jnp.float32)
    flat = (st["q"].astype(jnp.float32) * st["s"]).reshape(-1)
    size = int(np.prod(shape)) if shape else 1
    out = flat[:size].reshape(shape)
    if nonneg:
        out = out * out
    return out


class Adam(Optimizer):
    """``moment_dtype`` selects the optimizer-state precision (the HBM
    knob that decides the largest model one chip trains): ``None`` = f32
    (reference default), ``"bfloat16"`` = half-size moments,
    ``"int8"`` = blockwise-quantized moments (~1 byte each + 1/256 f32
    scales; the 8-bit-Adam recipe). The update math always runs f32."""

    _state_names = ["moment1", "moment2"]

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, amsgrad=False, moment_dtype=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad
        if moment_dtype not in (None, "bfloat16", "int8"):
            raise ValueError(
                f"moment_dtype must be None, 'bfloat16' or 'int8', got "
                f"{moment_dtype!r}")
        if amsgrad and moment_dtype == "int8":
            raise ValueError("amsgrad tracks a running max; int8 "
                             "requantization would drift it — use "
                             "moment_dtype='bfloat16' or None")
        self._moment_dtype = moment_dtype
        if amsgrad:
            self._state_names = self._state_names + ["moment2_max"]

    def _init_state(self, p):
        if self._moment_dtype is None:
            return super()._init_state(p)
        zero = jnp.zeros(tuple(p._data.shape), jnp.float32)
        return {name: _moment_encode(zero, self._moment_dtype,
                                     nonneg=name.startswith("moment2"))
                for name in self._state_names}

    def _state_to_checkpoint(self, name, v, p):
        if self._moment_dtype is None:
            return v
        return _moment_decode(v, tuple(p._data.shape), self._moment_dtype,
                              nonneg=name.startswith("moment2"))

    def _state_from_checkpoint(self, name, arr, p):
        if self._moment_dtype is None:
            return arr
        return _moment_encode(arr.astype(jnp.float32), self._moment_dtype,
                              nonneg=name.startswith("moment2"))

    def _update(self, w, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        g = self._apply_decay(w, g, wd_flag)
        b1, b2 = self._beta1, self._beta2
        md = self._moment_dtype
        t = step.astype(jnp.float32)
        shape = tuple(w.shape)
        m = b1 * _moment_decode(state["moment1"], shape, md) + (1 - b1) * g
        v = b2 * _moment_decode(state["moment2"], shape, md,
                                nonneg=True) + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** t)
        if self._amsgrad:
            v_max = jnp.maximum(
                _moment_decode(state["moment2_max"], shape, md,
                               nonneg=True), v)
            v_hat = v_max / (1 - b2 ** t)
            new_w = w - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
            return new_w, {"moment1": _moment_encode(m, md),
                           "moment2": _moment_encode(v, md, nonneg=True),
                           "moment2_max": _moment_encode(v_max, md,
                                                         nonneg=True)}
        v_hat = v / (1 - b2 ** t)
        new_w = w - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        return new_w, {"moment1": _moment_encode(m, md),
                       "moment2": _moment_encode(v, md, nonneg=True)}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 moment_dtype=None, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad, moment_dtype=moment_dtype,
                         name=name)
        self._wd_coeff = self._coeff(weight_decay)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _wd_flag(self, p):
        if getattr(p, "regularizer", None) is not None:
            return 0.0  # ParamAttr regularizer overrides decoupled wd
        if self._apply_decay_param_fun is not None:
            return 1.0 if self._apply_decay_param_fun(p.name) else 0.0
        return 1.0

    def _update(self, w, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        b1, b2 = self._beta1, self._beta2
        md = self._moment_dtype
        t = step.astype(jnp.float32)
        shape = tuple(w.shape)
        m = b1 * _moment_decode(state["moment1"], shape, md) + (1 - b1) * g
        v = b2 * _moment_decode(state["moment2"], shape, md,
                                nonneg=True) + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** t)
        v_hat = v / (1 - b2 ** t)
        w = w * (1 - lr * self._wd_coeff * wd_flag)
        new_w = w - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon)
        return new_w, {"moment1": _moment_encode(m, md),
                       "moment2": _moment_encode(v, md, nonneg=True)}


class Adagrad(Optimizer):
    _state_names = ["moment"]

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._epsilon = epsilon
        self._init_value = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full_like(self._fp32(p._data),
                                        self._init_value)}

    def _update(self, w, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        g = self._apply_decay(w, g, wd_flag)
        mom = state["moment"] + g * g
        return w - lr * g / (jnp.sqrt(mom) + self._epsilon), {"moment": mom}


class RMSProp(Optimizer):
    _state_names = ["mean_square", "mean_grad", "momentum"]

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update(self, w, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        g = self._apply_decay(w, g, wd_flag)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g * g
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum"] + lr * g / denom
        return w - mom, {"mean_square": ms, "mean_grad": mg, "momentum": mom}


class Adadelta(Optimizer):
    _state_names = ["avg_squared_grad", "avg_squared_update"]

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._epsilon = epsilon
        self._rho = rho

    def _update(self, w, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        g = self._apply_decay(w, g, wd_flag)
        asg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * g * g
        update = (jnp.sqrt(state["avg_squared_update"] + self._epsilon)
                  / jnp.sqrt(asg + self._epsilon)) * g
        asu = (self._rho * state["avg_squared_update"]
               + (1 - self._rho) * update * update)
        return w - lr * update, {"avg_squared_grad": asg,
                                 "avg_squared_update": asu}


class Adamax(Optimizer):
    _state_names = ["moment", "inf_norm"]

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update(self, w, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        g = self._apply_decay(w, g, wd_flag)
        t = step.astype(jnp.float32)
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g))
        new_w = w - (lr / (1 - self._beta1 ** t)) * m / (u + self._epsilon)
        return new_w, {"moment": m, "inf_norm": u}


class Lamb(Optimizer):
    _state_names = ["moment1", "moment2"]

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._lamb_wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _wd_flag(self, p):
        if self._exclude_fn is not None and self._exclude_fn(p):
            return 0.0
        return 1.0

    def _update(self, w, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        b1, b2 = self._beta1, self._beta2
        t = step.astype(jnp.float32)
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** t)
        v_hat = v / (1 - b2 ** t)
        r = (m_hat / (jnp.sqrt(v_hat) + self._epsilon)
             + self._lamb_wd * wd_flag * w)
        w_norm = jnp.sqrt(jnp.sum(w * w))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return w - lr * trust * r, {"moment1": m, "moment2": v}


class NAdam(Adam):
    def _update(self, w, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        g = self._apply_decay(w, g, wd_flag)
        b1, b2 = self._beta1, self._beta2
        t = step.astype(jnp.float32)
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        m_hat = (b1 * m + (1 - b1) * g) / (1 - b1 ** (t + 1))
        v_hat = v / (1 - b2 ** t)
        return (w - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon),
                {"moment1": m, "moment2": v})


class RAdam(Adam):
    def _update(self, w, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        g = self._apply_decay(w, g, wd_flag)
        b1, b2 = self._beta1, self._beta2
        t = step.astype(jnp.float32)
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** t)
        rho_inf = 2.0 / (1 - b2) - 1
        rho_t = rho_inf - 2 * t * b2 ** t / (1 - b2 ** t)

        def rect_update():
            r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf)
                         / ((rho_inf - 4) * (rho_inf - 2) * rho_t))
            v_hat = jnp.sqrt(v / (1 - b2 ** t))
            return w - lr * r * m_hat / (v_hat + self._epsilon)

        new_w = jnp.where(rho_t > 5, rect_update(), w - lr * m_hat)
        return new_w, {"moment1": m, "moment2": v}
