"""paddle_tpu.optimizer (reference: python/paddle/optimizer/__init__.py)."""
from . import lr
from .optimizer import (Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Momentum,
                        NAdam, Optimizer, RAdam, RMSProp, SGD)
from .extras import ASGD, LBFGS, Rprop
