"""ASGD, Rprop, LBFGS.

Reference contracts: ``python/paddle/optimizer/asgd.py`` (SAG averaged
gradient: ring buffer of the last ``batch_num`` grads, update by the
running average — :39 math block), ``python/paddle/optimizer/rprop.py``
(sign-agreement step-size adaptation within ``learning_rate_range``,
``etas`` shrink/grow), ``python/paddle/optimizer/lbfgs.py`` (torch-style
closure API, two-loop recursion over ``history_size`` curvature pairs,
optional strong-Wolfe line search).
"""
from __future__ import annotations

from typing import Callable, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor
from .optimizer import Optimizer

__all__ = ["ASGD", "Rprop", "LBFGS"]


class ASGD(Optimizer):
    """Stochastic Average Gradient (reference asgd.py:39):
    ``d ← d − y_i + g; y_i ← g; x ← x − lr·d/min(m+1, n)``."""

    _state_names = ["d", "ys", "m"]

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, multi_precision, name)
        self._n = int(batch_num)

    def _init_state(self, p):
        w = self._fp32(p._data)
        return {"d": jnp.zeros_like(w),
                "ys": jnp.zeros((self._n,) + w.shape, w.dtype),
                "m": jnp.zeros((), jnp.int32)}

    def _update(self, w, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        g = self._apply_decay(w, g, wd_flag)
        d, ys, m = state["d"], state["ys"], state["m"]
        idx = m % self._n
        y_old = ys[idx]
        d = d - y_old + g
        ys = ys.at[idx].set(g)
        denom = jnp.minimum(m + 1, self._n).astype(w.dtype)
        new_w = w - lr * d / denom
        return new_w, {"d": d, "ys": ys, "m": m + 1}


class Rprop(Optimizer):
    """Resilient backprop (reference rprop.py): per-weight step sizes
    grow by ``etas[1]`` on gradient sign agreement, shrink by
    ``etas[0]`` on sign flips (and the flip step is skipped), clipped
    to ``learning_rate_range``."""

    _state_names = ["prev_grad", "step_size"]

    def __init__(self, learning_rate=0.001,
                 learning_rate_range=(1e-5, 50.0), parameters=None,
                 etas=(0.5, 1.2), grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._lr_min, self._lr_max = map(float, learning_rate_range)
        self._etam, self._etap = map(float, etas)

    def _init_state(self, p):
        w = self._fp32(p._data)
        return {"prev_grad": jnp.zeros_like(w),
                "step_size": jnp.full_like(w, float(self.get_lr()))}

    def _update(self, w, g, master, state, lr, lr_mult, step, wd_flag=1.0):
        prev, size = state["prev_grad"], state["step_size"]
        sign = jnp.sign(g * prev)
        size = jnp.clip(
            jnp.where(sign > 0, size * self._etap,
                      jnp.where(sign < 0, size * self._etam, size)),
            self._lr_min, self._lr_max)
        g_eff = jnp.where(sign < 0, 0.0, g)   # skip flipped coords
        new_w = w - jnp.sign(g_eff) * size
        return new_w, {"prev_grad": g_eff, "step_size": size}


class LBFGS(Optimizer):
    """Limited-memory BFGS (reference lbfgs.py, torch-style closure
    API): two-loop recursion over the last ``history_size`` (s, y)
    pairs; ``line_search_fn='strong_wolfe'`` runs a cubic-interpolating
    Wolfe search, otherwise the raw ``learning_rate`` scales the
    direction."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, False, name)
        self._max_iter = max_iter
        self._max_eval = max_eval or max_iter * 5 // 4
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history = int(history_size)
        self._line_search = line_search_fn
        self._s: List[jnp.ndarray] = []
        self._y: List[jnp.ndarray] = []

    # ------------------------------------------------------- flat helpers
    def _params(self):
        return [p for p in self._parameter_list if not p.stop_gradient]

    def _flat(self, arrays):
        return jnp.concatenate([jnp.ravel(a.astype(jnp.float32))
                                for a in arrays])

    def _set_flat(self, vec):
        off = 0
        for p in self._params():
            n = int(np.prod(p.shape)) if p.shape else 1
            chunk = vec[off:off + n].reshape(p.shape).astype(p._data.dtype)
            p._swap_payload(chunk)
            off += n

    def _eval(self, closure):
        with dispatch.enable_grad():
            loss = closure()
            loss.backward()
        grads = self._flat([
            (p.grad._data if p.grad is not None
             else jnp.zeros(p.shape, jnp.float32))
            for p in self._params()])
        self.clear_grad()
        return float(loss.numpy()), grads

    def _direction(self, g):
        """Two-loop recursion over stored curvature pairs."""
        q = -g
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / jnp.vdot(y, s)
            a = rho * jnp.vdot(s, q)
            q = q - a * y
            alphas.append((a, rho, s, y))
        if self._s:
            s, y = self._s[-1], self._y[-1]
            q = q * (jnp.vdot(s, y) / jnp.vdot(y, y))
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.vdot(y, q)
            q = q + (a - b) * s
        return q

    def _wolfe(self, closure, x0, d, f0, g0, lr):
        """Backtracking + curvature (strong Wolfe) line search."""
        c1, c2 = 1e-4, 0.9
        dg0 = float(jnp.vdot(g0, d))  # tpulint: disable=TPU103 — Wolfe line search is host-driven BY CONTRACT (torch-style closure API re-runs arbitrary Python per probe); the directional derivative steers the host loop
        t = lr
        for _ in range(20):
            self._set_flat(x0 + t * d)
            f, g = self._eval(closure)
            if f > f0 + c1 * t * dg0:
                t *= 0.5
                continue
            if abs(float(jnp.vdot(g, d))) > c2 * abs(dg0):  # tpulint: disable=TPU103 — curvature condition decides the next HOST probe (shorten/lengthen t); inherently sequential, cannot trace
                t *= 1.5  # curvature not yet satisfied: lengthen
                continue
            return t, f, g
        self._set_flat(x0 + t * d)
        f, g = self._eval(closure)
        return t, f, g

    def step(self, closure: Optional[Callable] = None):
        if closure is None:
            raise RuntimeError(
                "LBFGS.step needs a closure re-evaluating the loss "
                "(reference lbfgs.py contract)")
        lr = float(self.get_lr())
        f, g = self._eval(closure)
        x = self._flat([p._data for p in self._params()])
        evals = 1
        for _ in range(self._max_iter):
            if float(jnp.abs(g).max()) <= self._tol_grad:  # tpulint: disable=TPU103 — convergence break of the outer HOST iteration (each iter re-evaluates the Python closure); a data-dependent loop bound is host-by-design here
                break
            d = self._direction(g)
            if self._line_search == "strong_wolfe":
                t, f_new, g_new = self._wolfe(closure, x, d, f, g, lr)
                evals += 1
            else:
                t = lr
                self._set_flat(x + t * d)
                f_new, g_new = self._eval(closure)
                evals += 1
            x_new = x + t * d
            s = x_new - x
            ygrad = g_new - g
            if float(jnp.vdot(s, ygrad)) > 1e-10:  # tpulint: disable=TPU103 — curvature-pair admission gates PYTHON list state (the (s,y) history the two-loop recursion closes over); host decision by design
                self._s.append(s)
                self._y.append(ygrad)
                if len(self._s) > self._history:
                    self._s.pop(0)
                    self._y.pop(0)
            small_step = float(jnp.abs(s).max()) <= self._tol_change  # tpulint: disable=TPU103 — step-size/loss-change convergence break of the host iteration (same contract as the gradient-norm break above)
            if small_step or abs(f_new - f) <= self._tol_change:
                x, f, g = x_new, f_new, g_new
                break
            x, f, g = x_new, f_new, g_new
            if evals >= self._max_eval:
                break
        self._set_flat(x)
        self._post_step()
        return Tensor(jnp.asarray(f, jnp.float32))
