"""paddle.device — device management + memory stats facade.

Capability parity with the reference device module (reference:
python/paddle/device/__init__.py set_device/get_device;
python/paddle/device/cuda/__init__.py memory_allocated / max_memory_* over
paddle/fluid/memory/stats.cc). TPU-native: device stats come from the XLA
client's per-device memory_stats(); host stats from the native tracked
allocator (paddle_tpu/native)."""
from __future__ import annotations

import jax

from ..core.place import (get_device, set_device)  # noqa: F401


def device_count() -> int:
    return jax.device_count()


def _stats(device_id: int = 0) -> dict:
    try:
        return jax.local_devices()[device_id].memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None) -> int:
    """Bytes currently allocated on the accelerator (reference
    device/cuda memory_allocated)."""
    return int(_stats(_id(device)).get("bytes_in_use", 0))


def max_memory_allocated(device=None) -> int:
    return int(_stats(_id(device)).get("peak_bytes_in_use", 0))


def memory_reserved(device=None) -> int:
    s = _stats(_id(device))
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None) -> int:
    return max_memory_allocated(device)


def host_memory_stats() -> dict:
    from .. import native
    return native.host_memory_stats()


def _id(device) -> int:
    if device is None:
        return 0
    if isinstance(device, int):
        return device
    s = str(device)
    return int(s.split(":")[-1]) if ":" in s else 0


class cuda:
    """Source-compat shim: paddle.device.cuda.* (accelerator = TPU)."""
    memory_allocated = staticmethod(memory_allocated)
    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_reserved = staticmethod(memory_reserved)
    max_memory_reserved = staticmethod(max_memory_reserved)

    @staticmethod
    def device_count():
        return jax.device_count()

    @staticmethod
    def synchronize(device=None):
        import jax
        (jax.device_put(0) + 0).block_until_ready()


__all__ = ["device_count", "get_device", "set_device", "memory_allocated",
           "max_memory_allocated", "memory_reserved",
           "max_memory_reserved", "host_memory_stats", "cuda"]
