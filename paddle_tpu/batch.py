"""paddle.batch — legacy batched-reader combinator (reference:
python/paddle/batch.py)."""
from __future__ import annotations

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    """Wrap an item reader into a mini-batch reader.

    ``reader`` is a zero-arg callable returning an iterable; the result
    is the same, yielding lists of ``batch_size`` items (final short
    batch kept unless ``drop_last``).
    """
    if batch_size <= 0:
        raise ValueError(
            f"batch_size should be a positive integer, got {batch_size}")

    def batch_reader():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader
