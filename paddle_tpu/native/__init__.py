"""Native (C++) runtime bindings.

Builds ``src/ptruntime.cc`` into a shared library on first import (g++,
cached beside the source) and binds it with ctypes — the image has no
pybind11, and the C ABI keeps the boundary trivial. Falls back cleanly
(``AVAILABLE = False``) when no compiler is present so pure-Python paths
keep working.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "ptruntime.cc")

AVAILABLE = False
_lib = None
_lock = threading.Lock()


def _build() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_HERE, f"_ptruntime_{digest}.so")
    if os.path.exists(out):
        return out
    tmp = out + ".tmp"
    subprocess.run(
        ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
         _SRC, "-o", tmp],
        check=True, capture_output=True)
    os.replace(tmp, out)
    return out


def _load():
    global _lib, AVAILABLE
    with _lock:
        if _lib is not None or AVAILABLE:
            return _lib
        try:
            lib = ctypes.CDLL(_build())
        except Exception:
            return None
        lib.pt_collate.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int]
        lib.pt_host_alloc.restype = ctypes.c_void_p
        lib.pt_host_alloc.argtypes = [ctypes.c_int64]
        lib.pt_host_free.argtypes = [ctypes.c_void_p]
        for fn in ("pt_host_allocated", "pt_host_peak",
                   "pt_host_alloc_count"):
            getattr(lib, fn).restype = ctypes.c_int64
        _lib = lib
        AVAILABLE = True
        return lib


_load()


def collate_stack(arrays, n_threads: int = 0) -> np.ndarray:
    """Stack same-shape numpy arrays into one contiguous batch using the
    native parallel memcpy; equivalent to np.stack(arrays)."""
    lib = _lib
    if lib is None:
        return np.stack(arrays)
    # validate BEFORE any allocation/copies: shape (not just nbytes) and
    # dtype must match, else defer to np.stack (which raises on ragged)
    shape, dtype = arrays[0].shape, arrays[0].dtype
    for a in arrays:
        if a.shape != shape or a.dtype != dtype:
            return np.stack(arrays)
    n = len(arrays)
    contigs = [np.ascontiguousarray(a) for a in arrays]
    out = np.empty((n,) + shape, dtype)
    ptrs = (ctypes.c_void_p * n)(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in contigs])
    if n_threads <= 0:
        n_threads = min(max(os.cpu_count() // 2, 1), 8)
    lib.pt_collate(ptrs, n, contigs[0].nbytes,
                   out.ctypes.data_as(ctypes.c_void_p), n_threads)
    return out


def host_memory_stats() -> dict:
    """Host allocation stats of the native tracked allocator (reference
    memory/stats.cc facade)."""
    if _lib is None:
        return {"allocated": 0, "peak": 0, "alloc_count": 0,
                "native": False}
    return {"allocated": int(_lib.pt_host_allocated()),
            "peak": int(_lib.pt_host_peak()),
            "alloc_count": int(_lib.pt_host_alloc_count()),
            "native": True}


class HostBuffer:
    """A tracked, 64-byte-aligned host buffer (native allocator).

    Views handed out by :meth:`as_array` are tracked (weakly); ``free()``
    refuses while any view is alive so the memory can never be pulled out
    from under a live ndarray."""

    def __init__(self, nbytes: int):
        if _lib is None:
            raise RuntimeError("native runtime unavailable")
        self._ptr = _lib.pt_host_alloc(nbytes)
        if not self._ptr:
            raise MemoryError(f"pt_host_alloc({nbytes}) failed")
        self.nbytes = nbytes
        self._views = []

    def as_array(self, shape, dtype) -> np.ndarray:
        import weakref
        if not self._ptr:
            raise RuntimeError("buffer already freed")
        n = int(np.prod(shape)) * np.dtype(dtype).itemsize
        if n > self.nbytes:
            raise ValueError("buffer too small")
        buf = (ctypes.c_char * self.nbytes).from_address(self._ptr)
        arr = np.frombuffer(buf, dtype=dtype,
                            count=int(np.prod(shape))).reshape(shape)
        self._views = [r for r in self._views if r() is not None]
        self._views.append(weakref.ref(arr))
        return arr

    def _live_views(self) -> int:
        self._views = [r for r in self._views if r() is not None]
        return len(self._views)

    def free(self):
        if self._ptr:
            if self._live_views():
                raise RuntimeError(
                    f"{self._live_views()} live array view(s) reference "
                    "this buffer; drop them before free()")
            _lib.pt_host_free(self._ptr)
            self._ptr = None

    def __del__(self):
        # leak rather than dangle if views outlive the buffer object
        try:
            if self._ptr and not self._live_views():
                _lib.pt_host_free(self._ptr)
                self._ptr = None
        except Exception:
            pass


__all__ = ["AVAILABLE", "collate_stack", "host_memory_stats", "HostBuffer"]
