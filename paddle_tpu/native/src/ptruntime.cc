// paddle_tpu native runtime: parallel batch collation + tracked host
// allocator.
//
// Capability parity with the reference's native runtime pieces the Python
// layer leans on (reference: paddle/fluid/framework/data_feed.cc native
// batch assembly in the C++ DataLoader workers; paddle/fluid/memory/
// stats.cc host/device stat registry). TPU-native: device memory belongs
// to XLA, so the native layer owns the HOST side of the pipeline — the
// memcpy-bound sample->batch collation that feeds jax.device_put, and a
// host allocation tracker behind paddle_tpu.device.memory_stats.
//
// Built at import by paddle_tpu/native/__init__.py (g++ -O3 -shared);
// exposed over the C ABI via ctypes (no pybind11 in this image).

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------
// Parallel collation: stack n same-size sample buffers into dst.
// Threads split WORK (bytes), not samples, so a few large samples still
// parallelize: each thread owns a contiguous byte range of the OUTPUT and
// copies the (sample, offset) pieces that fall in it.
// ---------------------------------------------------------------------
void pt_collate(const void** srcs, int64_t n, int64_t sample_bytes,
                void* dst, int n_threads) {
  if (n <= 0 || sample_bytes <= 0) return;
  char* out = static_cast<char*>(dst);
  int64_t total = n * sample_bytes;
  if (n_threads <= 1 || total < (int64_t)1 << 20) {
    for (int64_t i = 0; i < n; ++i)
      std::memcpy(out + i * sample_bytes, srcs[i], sample_bytes);
    return;
  }
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  int64_t per = (total + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * per, hi = std::min(total, lo + per);
    if (lo >= hi) break;
    threads.emplace_back([=] {
      int64_t pos = lo;
      while (pos < hi) {
        int64_t sample = pos / sample_bytes;
        int64_t off = pos - sample * sample_bytes;
        int64_t chunk = std::min(sample_bytes - off, hi - pos);
        std::memcpy(out + pos,
                    static_cast<const char*>(srcs[sample]) + off, chunk);
        pos += chunk;
      }
    });
  }
  for (auto& th : threads) th.join();
}

// ---------------------------------------------------------------------
// Tracked host allocator (stats facade).
// ---------------------------------------------------------------------
static std::atomic<int64_t> g_allocated{0};
static std::atomic<int64_t> g_peak{0};
static std::atomic<int64_t> g_alloc_count{0};

struct Header {
  int64_t bytes;
  int64_t magic;
};
static constexpr int64_t kMagic = 0x70746e61746976;  // "ptnativ"
static constexpr size_t kAlign = 64;

void* pt_host_alloc(int64_t bytes) {
  size_t total = sizeof(Header) + kAlign + (size_t)bytes;
  char* raw = static_cast<char*>(std::malloc(total));
  if (!raw) return nullptr;
  char* user = raw + sizeof(Header);
  user += kAlign - (reinterpret_cast<uintptr_t>(user) % kAlign);
  Header* h = reinterpret_cast<Header*>(user) - 1;
  h->bytes = bytes;
  h->magic = kMagic ^ reinterpret_cast<int64_t>(raw);
  // stash raw pointer just before the header
  std::memcpy(reinterpret_cast<char*>(h) - sizeof(void*), &raw,
              sizeof(void*));
  int64_t cur = g_allocated.fetch_add(bytes) + bytes;
  int64_t peak = g_peak.load();
  while (cur > peak && !g_peak.compare_exchange_weak(peak, cur)) {
  }
  g_alloc_count.fetch_add(1);
  return user;
}

void pt_host_free(void* p) {
  if (!p) return;
  Header* h = reinterpret_cast<Header*>(p) - 1;
  void* raw;
  std::memcpy(&raw, reinterpret_cast<char*>(h) - sizeof(void*),
              sizeof(void*));
  if ((h->magic ^ reinterpret_cast<int64_t>(raw)) != kMagic) return;
  g_allocated.fetch_sub(h->bytes);
  std::free(raw);
}

int64_t pt_host_allocated() { return g_allocated.load(); }
int64_t pt_host_peak() { return g_peak.load(); }
int64_t pt_host_alloc_count() { return g_alloc_count.load(); }
void pt_reset_peak() { g_peak.store(g_allocated.load()); }

}  // extern "C"
