"""paddle.geometric — graph learning ops (segment reductions, message
passing, neighbor sampling, reindex).

Reference: python/paddle/geometric/ (math.py segment_*, message_passing/
send_recv.py send_u_recv:36 / send_ue_recv / send_uv, sampling/neighbors.py,
reindex.py) over phi kernels (segment_pool_kernel, graph_send_recv_kernel,
graph_sample_neighbors_kernel).

TPU-native design: segment reductions and message passing lower to
``jax.ops.segment_*`` — XLA scatter-reduce, which is exactly the TPU shape
of the reference's CUDA atomic-scatter kernels, and differentiable through
``dispatch.call`` for the training-path ops. Neighbor sampling and reindex
are host-side (data-dependent shapes, dataloader territory) like the
reference CPU kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor, as_tensor

__all__ = [
    "send_u_recv", "send_ue_recv", "send_uv", "segment_sum", "segment_mean",
    "segment_min", "segment_max", "reindex_graph", "reindex_heter_graph",
    "sample_neighbors", "weighted_sample_neighbors",
]


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _segment(data, segment_ids, mode, op_name):
    data, seg = _t(data), _t(segment_ids)
    # jax.ops.segment_* need a STATIC num_segments (it is the output
    # shape); the reference API derives it from the data, so this one
    # host read is the designed boundary — the reduction itself stays
    # on device through dispatch.call below.
    # tpulint: disable=TPU103,TPU104 static num_segments requires host max
    n_seg = int(np.asarray(seg._data).max()) + 1 if seg.size else 0

    def f(d, s):
        s = s.astype(jnp.int32)
        if mode == "sum":
            return jax.ops.segment_sum(d, s, num_segments=n_seg)
        if mode == "mean":
            tot = jax.ops.segment_sum(d, s, num_segments=n_seg)
            cnt = jax.ops.segment_sum(jnp.ones((d.shape[0],), d.dtype), s,
                                      num_segments=n_seg)
            cnt = jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (d.ndim - 1))
            return tot / cnt
        if mode == "min":
            out = jax.ops.segment_min(d, s, num_segments=n_seg)
        else:
            out = jax.ops.segment_max(d, s, num_segments=n_seg)
        # empty segments: the reference memsets output to 0
        # (phi/kernels/impl/segment_pool_kernel_impl.h)
        touched = jax.ops.segment_sum(
            jnp.ones((d.shape[0],), jnp.int32), s, num_segments=n_seg) > 0
        return jnp.where(
            touched.reshape((-1,) + (1,) * (d.ndim - 1)), out,
            jnp.zeros((), d.dtype))

    return dispatch.call(op_name, f, [data, seg],
                         differentiable_mask=[True, False])


def segment_sum(data, segment_ids, name=None):
    """Sum-reduce rows of ``data`` by segment id (reference
    python/paddle/geometric/math.py:23)."""
    return _segment(data, segment_ids, "sum", "segment_sum")


def segment_mean(data, segment_ids, name=None):
    """Mean of ``data`` rows per segment id (jax segment ops; reference
    paddle.geometric.segment_mean)."""
    return _segment(data, segment_ids, "mean", "segment_mean")


def segment_min(data, segment_ids, name=None):
    """Min of ``data`` rows per segment id (reference
    paddle.geometric.segment_min)."""
    return _segment(data, segment_ids, "min", "segment_min")


def segment_max(data, segment_ids, name=None):
    """Max of ``data`` rows per segment id (reference
    paddle.geometric.segment_max)."""
    return _segment(data, segment_ids, "max", "segment_max")


def _recv_reduce(msgs, dst, n_out, reduce_op, dtype):
    dst = dst.astype(jnp.int32)
    if reduce_op == "sum":
        return jax.ops.segment_sum(msgs, dst, num_segments=n_out)
    if reduce_op == "mean":
        tot = jax.ops.segment_sum(msgs, dst, num_segments=n_out)
        cnt = jax.ops.segment_sum(jnp.ones((msgs.shape[0],), dtype), dst,
                                  num_segments=n_out)
        return tot / jnp.maximum(cnt, 1).reshape(
            (-1,) + (1,) * (msgs.ndim - 1))
    if reduce_op == "min":
        out = jax.ops.segment_min(msgs, dst, num_segments=n_out)
    else:
        out = jax.ops.segment_max(msgs, dst, num_segments=n_out)
    # untouched rows hold the dtype identity (inf / INT_MAX); the reference
    # zeroes them — mask by touched-ness, which also covers integer dtypes
    touched = jax.ops.segment_sum(
        jnp.ones((msgs.shape[0],), jnp.int32), dst, num_segments=n_out) > 0
    return jnp.where(touched.reshape((-1,) + (1,) * (msgs.ndim - 1)), out,
                     jnp.zeros((), msgs.dtype))


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] and scatter-reduce onto dst (reference
    python/paddle/geometric/message_passing/send_recv.py:36)."""
    x, src, dst = _t(x), _t(src_index), _t(dst_index)
    n_out = int(out_size) if out_size is not None else x.shape[0]

    def f(xa, s, d):
        return _recv_reduce(xa[s.astype(jnp.int32)], d, n_out, reduce_op,
                            xa.dtype)

    return dispatch.call("send_u_recv", f, [x, src, dst],
                         differentiable_mask=[True, False, False])


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine x[src] with edge feature y (add/sub/mul/div), then
    scatter-reduce onto dst (reference send_recv.py send_ue_recv)."""
    x, y, src, dst = _t(x), _t(y), _t(src_index), _t(dst_index)
    n_out = int(out_size) if out_size is not None else x.shape[0]

    def f(xa, ya, s, d):
        m = xa[s.astype(jnp.int32)]
        if message_op == "add":
            m = m + ya
        elif message_op == "sub":
            m = m - ya
        elif message_op == "mul":
            m = m * ya
        elif message_op == "div":
            m = m / ya
        else:
            raise ValueError(f"unknown message_op {message_op}")
        return _recv_reduce(m, d, n_out, reduce_op, m.dtype)

    return dispatch.call("send_ue_recv", f, [x, y, src, dst],
                         differentiable_mask=[True, True, False, False])


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints: x[src] (op) y[dst]
    (reference send_recv.py send_uv)."""
    x, y, src, dst = _t(x), _t(y), _t(src_index), _t(dst_index)

    def f(xa, ya, s, d):
        a = xa[s.astype(jnp.int32)]
        b = ya[d.astype(jnp.int32)]
        if message_op == "add":
            return a + b
        if message_op == "sub":
            return a - b
        if message_op == "mul":
            return a * b
        if message_op == "div":
            return a / b
        raise ValueError(f"unknown message_op {message_op}")

    return dispatch.call("send_uv", f, [x, y, src, dst],
                         differentiable_mask=[True, True, False, False])


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to a local contiguous space.

    Returns (reindex_src, reindex_dst, out_nodes) where out_nodes is
    [x, unique new neighbors] and reindex_* are edges in local ids.
    Reference: python/paddle/geometric/reindex.py reindex_graph,
    phi/kernels/gpu/graph_reindex_kernel.cu. Host-side BY DESIGN: the
    output node set's size and first-occurrence order are data-dependent
    (an in-graph jnp.unique(size=...) would sort, breaking reference
    order parity), and the op sits in the sampler pipeline next to the
    dataloader, never inside the training graph — same split as the
    reference's CPU reindex kernel. tpulint suppressions below mark that
    designed host boundary.
    """
    xs = np.asarray(_t(x)._data).ravel()        # tpulint: disable=TPU104 host sampler op
    nb = np.asarray(_t(neighbors)._data).ravel()  # tpulint: disable=TPU104 host sampler op
    cnt = np.asarray(_t(count)._data).ravel()   # tpulint: disable=TPU104 host sampler op
    mapping = {}
    out_nodes = []
    for v in xs.tolist():                       # tpulint: disable=TPU102 first-occurrence order is host logic
        if v not in mapping:
            mapping[v] = len(out_nodes)
            out_nodes.append(v)
    for v in nb.tolist():                       # tpulint: disable=TPU102 first-occurrence order is host logic
        if v not in mapping:
            mapping[v] = len(out_nodes)
            out_nodes.append(v)
    # tpulint: disable=TPU102 dict lookup per edge is host logic
    reindex_src = np.asarray([mapping[v] for v in nb.tolist()], np.int64)
    dst = np.repeat(np.arange(xs.shape[0]), cnt)  # tpulint: disable=TPU104 ragged repeat, host sampler op
    reindex_dst = dst.astype(np.int64)
    return (Tensor(jnp.asarray(reindex_src.astype(np.int32))),
            Tensor(jnp.asarray(reindex_dst.astype(np.int32))),
            Tensor(jnp.asarray(np.asarray(out_nodes, np.int32))))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: neighbors/count are lists per edge type
    (reference reindex.py reindex_heter_graph). Host-side by design for
    the same reasons as :func:`reindex_graph` (data-dependent output
    shape + first-occurrence order, sampler pipeline)."""
    xs = np.asarray(_t(x)._data).ravel()        # tpulint: disable=TPU104 host sampler op
    mapping = {}
    out_nodes = []
    for v in xs.tolist():                       # tpulint: disable=TPU102 first-occurrence order is host logic
        if v not in mapping:
            mapping[v] = len(out_nodes)
            out_nodes.append(v)
    srcs, dsts = [], []
    for nb_t, cnt_t in zip(neighbors, count):
        nb = np.asarray(_t(nb_t)._data).ravel()   # tpulint: disable=TPU104 host sampler op
        cnt = np.asarray(_t(cnt_t)._data).ravel()  # tpulint: disable=TPU104 host sampler op
        for v in nb.tolist():                   # tpulint: disable=TPU102 first-occurrence order is host logic
            if v not in mapping:
                mapping[v] = len(out_nodes)
                out_nodes.append(v)
        # tpulint: disable=TPU102 dict lookup per edge is host logic
        srcs.append(np.asarray([mapping[v] for v in nb.tolist()], np.int64))
        # tpulint: disable=TPU104 ragged repeat, host sampler op
        dsts.append(np.repeat(np.arange(xs.shape[0]), cnt).astype(np.int64))
    return (Tensor(jnp.asarray(np.concatenate(srcs).astype(np.int32))),
            Tensor(jnp.asarray(np.concatenate(dsts).astype(np.int32))),
            Tensor(jnp.asarray(np.asarray(out_nodes, np.int32))))


def _csr_neighbors(colptr, nodes):
    """Per-query-node (start, end) spans into CSC/CSR storage (host)."""
    ptr = np.asarray(colptr).ravel()
    return [(int(ptr[v]), int(ptr[v + 1])) for v in nodes.tolist()]


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniformly sample up to ``sample_size`` in-neighbors per node from
    CSC storage (reference python/paddle/geometric/sampling/neighbors.py,
    phi/kernels/gpu/graph_sample_neighbors_kernel.cu). Host-side sampler
    BY DESIGN: per-node degrees make every output ragged
    (data-dependent shapes) and the op feeds the dataloader, mirroring
    the reference's CPU sampling kernel — suppressions below mark the
    designed host boundary.
    """
    from ..core.generator import default_generator
    nodes = np.asarray(_t(input_nodes)._data).ravel()  # tpulint: disable=TPU104 host sampler op
    rownp = np.asarray(_t(row)._data).ravel()   # tpulint: disable=TPU104 host sampler op
    spans = _csr_neighbors(np.asarray(_t(colptr)._data), nodes)  # tpulint: disable=TPU104 host sampler op
    eid_np = (np.asarray(_t(eids)._data).ravel()  # tpulint: disable=TPU104 host sampler op
              if eids is not None else None)
    key = default_generator().next_key()
    rng = np.random.RandomState(
        int(jax.random.randint(key, (), 0, 2**31 - 1)))  # tpulint: disable=TPU103 seed the host RNG once
    out, cnt, oeids = [], [], []
    for lo, hi in spans:
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:  # tpulint: disable=TPU105 ragged per-node branch, host sampler
            pick = np.arange(lo, hi)            # tpulint: disable=TPU104 host sampler op
        else:
            pick = lo + rng.choice(deg, size=sample_size, replace=False)
        out.append(rownp[pick])
        cnt.append(pick.shape[0])
        if eid_np is not None:
            oeids.append(eid_np[pick])
    out_nb = Tensor(jnp.asarray(
        np.concatenate(out) if out else np.zeros((0,), rownp.dtype)))
    out_cnt = Tensor(jnp.asarray(np.asarray(cnt, np.int32)))
    if return_eids:
        if eid_np is None:
            raise ValueError("return_eids=True requires eids")
        return out_nb, out_cnt, Tensor(jnp.asarray(np.concatenate(oeids)))
    return out_nb, out_cnt


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weighted (without-replacement) neighbor sampling — probability
    proportional to edge weight (reference weighted_sample_neighbors,
    phi/kernels/gpu/weighted_sample_neighbors_kernel.cu). Host-side
    sampler by design — see :func:`sample_neighbors`."""
    from ..core.generator import default_generator
    nodes = np.asarray(_t(input_nodes)._data).ravel()  # tpulint: disable=TPU104 host sampler op
    rownp = np.asarray(_t(row)._data).ravel()   # tpulint: disable=TPU104 host sampler op
    wnp = np.asarray(_t(edge_weight)._data).ravel().astype(np.float64)  # tpulint: disable=TPU104 host sampler op
    spans = _csr_neighbors(np.asarray(_t(colptr)._data), nodes)  # tpulint: disable=TPU104 host sampler op
    eid_np = (np.asarray(_t(eids)._data).ravel()  # tpulint: disable=TPU104 host sampler op
              if eids is not None else None)
    key = default_generator().next_key()
    rng = np.random.RandomState(
        int(jax.random.randint(key, (), 0, 2**31 - 1)))  # tpulint: disable=TPU103 seed the host RNG once
    out, cnt, oeids = [], [], []
    for lo, hi in spans:
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:  # tpulint: disable=TPU105 ragged per-node branch, host sampler
            pick = np.arange(lo, hi)            # tpulint: disable=TPU104 host sampler op
        else:
            w = wnp[lo:hi]
            p = w / w.sum() if w.sum() > 0 else None
            pick = lo + rng.choice(deg, size=sample_size, replace=False, p=p)
        out.append(rownp[pick])
        cnt.append(pick.shape[0])
        if eid_np is not None:
            oeids.append(eid_np[pick])
    out_nb = Tensor(jnp.asarray(
        np.concatenate(out) if out else np.zeros((0,), rownp.dtype)))
    out_cnt = Tensor(jnp.asarray(np.asarray(cnt, np.int32)))
    if return_eids:
        if eid_np is None:
            raise ValueError("return_eids=True requires eids")
        return out_nb, out_cnt, Tensor(jnp.asarray(np.concatenate(oeids)))
    return out_nb, out_cnt
