"""paddle.fft — discrete Fourier transform surface.

Capability parity with the reference fft module (reference:
python/paddle/fft.py — fft/ifft/rfft/irfft + 2d/nd variants, hfft family,
fftfreq/fftshift helpers, norm= forward|backward|ortho). TPU-native: thin
dispatch lowerings onto jnp.fft (XLA FFT HLO), differentiable through the
tape like every other op.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core import dispatch
from .core.tensor import Tensor, as_tensor


def _t(x):
    return x if isinstance(x, Tensor) else as_tensor(x)


def _norm(norm):
    if norm not in ("forward", "backward", "ortho"):
        raise ValueError(f"norm must be forward/backward/ortho, got {norm}")
    return norm


def _mk1(opname, jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return dispatch.call(
            opname, lambda a: jfn(a, n=n, axis=axis, norm=_norm(norm)),
            [_t(x)])
    op.__name__ = opname
    return op


def _mk2(opname, jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return dispatch.call(
            opname, lambda a: jfn(a, s=s, axes=tuple(axes),
                                  norm=_norm(norm)), [_t(x)])
    op.__name__ = opname
    return op


def _mkn(opname, jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return dispatch.call(
            opname, lambda a: jfn(a, s=s,
                                  axes=None if axes is None else
                                  tuple(axes),
                                  norm=_norm(norm)), [_t(x)])
    op.__name__ = opname
    return op


fft = _mk1("fft", jnp.fft.fft)
ifft = _mk1("ifft", jnp.fft.ifft)
rfft = _mk1("rfft", jnp.fft.rfft)
irfft = _mk1("irfft", jnp.fft.irfft)
hfft = _mk1("hfft", jnp.fft.hfft)
ihfft = _mk1("ihfft", jnp.fft.ihfft)

fft2 = _mk2("fft2", jnp.fft.fft2)
ifft2 = _mk2("ifft2", jnp.fft.ifft2)
rfft2 = _mk2("rfft2", jnp.fft.rfft2)
irfft2 = _mk2("irfft2", jnp.fft.irfft2)

fftn = _mkn("fftn", jnp.fft.fftn)
ifftn = _mkn("ifftn", jnp.fft.ifftn)
rfftn = _mkn("rfftn", jnp.fft.rfftn)
irfftn = _mkn("irfftn", jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype="float32", name=None):
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype))


def rfftfreq(n, d=1.0, dtype="float32", name=None):
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype))


def fftshift(x, axes=None, name=None):
    return dispatch.call("fftshift",
                         lambda a: jnp.fft.fftshift(a, axes=axes), [_t(x)])


def ifftshift(x, axes=None, name=None):
    return dispatch.call("ifftshift",
                         lambda a: jnp.fft.ifftshift(a, axes=axes), [_t(x)])


# Hermitian multi-dim transforms (reference hfft2/hfftn/ihfft2/ihfftn).
# jnp has only the 1-D hermitian pair; the N-D versions follow the
# standard identities hfftₙₒᵣₘ(a) = irfftᵢₙᵥ₋ₙₒᵣₘ(conj(a)) and
# ihfftₙₒᵣₘ(a) = conj(rfftᵢₙᵥ₋ₙₒᵣₘ(a)) with backward↔forward swapped
# (ortho is self-inverse) — the scaling argument scipy.fft uses.
_INV_NORM = {"backward": "forward", "forward": "backward",
             "ortho": "ortho"}


def _mk_hfftn(opname, axes_default, two_d):
    def op(x, s=None, axes=axes_default, norm="backward", name=None):
        inv = _INV_NORM[_norm(norm) or "backward"]

        def f(a):
            ax = tuple(axes) if axes is not None else (
                (-2, -1) if two_d else tuple(range(a.ndim)))
            return jnp.fft.irfftn(jnp.conj(a), s=s, axes=ax, norm=inv)
        return dispatch.call(opname, f, [_t(x)])
    op.__name__ = opname
    return op


def _mk_ihfftn(opname, axes_default, two_d):
    def op(x, s=None, axes=axes_default, norm="backward", name=None):
        inv = _INV_NORM[_norm(norm) or "backward"]

        def f(a):
            ax = tuple(axes) if axes is not None else (
                (-2, -1) if two_d else tuple(range(a.ndim)))
            return jnp.conj(jnp.fft.rfftn(a, s=s, axes=ax, norm=inv))
        return dispatch.call(opname, f, [_t(x)])
    op.__name__ = opname
    return op


hfft2 = _mk_hfftn("hfft2", (-2, -1), True)
ihfft2 = _mk_ihfftn("ihfft2", (-2, -1), True)
hfftn = _mk_hfftn("hfftn", None, False)
ihfftn = _mk_ihfftn("ihfftn", None, False)


__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2",
           "ifft2", "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn",
           "hfft2", "ihfft2", "hfftn", "ihfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]
