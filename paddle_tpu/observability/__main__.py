"""``python -m paddle_tpu.observability`` — print the metrics snapshot.

    python -m paddle_tpu.observability                  # live registry, prom
    python -m paddle_tpu.observability --format json
    python -m paddle_tpu.observability --input /tmp/metrics.json
    python -m paddle_tpu.observability --merge /tmp/metrics.json

Without ``--input`` the snapshot is of THIS process's registry (mostly the
callback gauges, e.g. device memory, unless run embedded); with ``--input``
it renders a snapshot written by ``PADDLE_TPU_METRICS_DUMP=/path`` from an
instrumented run. ``--merge BASE`` folds BASE plus every per-process
sibling (``BASE.rankN`` from distributed ranks, ``BASE.pidN`` from
dataloader workers) into one aggregate whose series carry a leading
``rank`` label — the multi-process dump files stop being orphans. Exit
status 0 unless the input file(s) are unreadable.
"""
from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.observability",
        description="print the framework metrics snapshot")
    ap.add_argument("--format", choices=("prom", "json"), default="prom",
                    help="output format (default: Prometheus text)")
    ap.add_argument("--input", help="render a saved JSON snapshot file "
                    "instead of this process's registry")
    ap.add_argument("--merge", metavar="BASE",
                    help="fold BASE + BASE.rankN/.pidN snapshot files "
                    "into one rank-labeled aggregate and render it")
    ap.add_argument("--output", help="write the rendered output to a "
                    "file instead of stdout")
    args = ap.parse_args(argv)

    if args.merge:
        from .fleet import merge_snapshot_files
        try:
            snap = merge_snapshot_files(args.merge)
        except (OSError, ValueError) as e:
            print(f"cannot merge snapshots at {args.merge!r}: {e}",
                  file=sys.stderr)
            return 1
    elif args.input:
        try:
            with open(args.input) as f:
                snap = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read snapshot {args.input!r}: {e}",
                  file=sys.stderr)
            return 1
    else:
        from . import REGISTRY
        try:
            # attributed HBM gauges are census-time: refresh before dump
            from .perf import memory as _perf_memory
            _perf_memory.refresh_metrics()
        except Exception:
            pass
        snap = REGISTRY.snapshot()

    if args.format == "json":
        text = json.dumps(snap, indent=1, sort_keys=True) + "\n"
    else:
        from .metrics import render_prometheus
        text = render_prometheus(snap)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
