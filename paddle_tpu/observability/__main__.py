"""``python -m paddle_tpu.observability`` — print the metrics snapshot.

    python -m paddle_tpu.observability                  # live registry, prom
    python -m paddle_tpu.observability --format json
    python -m paddle_tpu.observability --input /tmp/metrics.json

Without ``--input`` the snapshot is of THIS process's registry (mostly the
callback gauges, e.g. device memory, unless run embedded); with ``--input``
it renders a snapshot written by ``PADDLE_TPU_METRICS_DUMP=/path`` from an
instrumented run. Exit status 0 unless the input file is unreadable.
"""
from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="paddle_tpu.observability",
        description="print the framework metrics snapshot")
    ap.add_argument("--format", choices=("prom", "json"), default="prom",
                    help="output format (default: Prometheus text)")
    ap.add_argument("--input", help="render a saved JSON snapshot file "
                    "instead of this process's registry")
    args = ap.parse_args(argv)

    if args.input:
        try:
            with open(args.input) as f:
                snap = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read snapshot {args.input!r}: {e}",
                  file=sys.stderr)
            return 1
    else:
        from . import REGISTRY
        try:
            # attributed HBM gauges are census-time: refresh before dump
            from .perf import memory as _perf_memory
            _perf_memory.refresh_metrics()
        except Exception:
            pass
        snap = REGISTRY.snapshot()

    if args.format == "json":
        print(json.dumps(snap, indent=1, sort_keys=True))
    else:
        from .metrics import render_prometheus
        sys.stdout.write(render_prometheus(snap))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
